"""Engine configuration (ref: src/storage/src/config.rs).

Field names and defaults track the reference's TOML keys so configs are
interchangeable: scheduler (config.rs:24-50), parquet encodings (52-94),
per-column overrides (96-103), write props (105-133), manifest (135-155),
UpdateMode (166-172).  Unknown keys are rejected (serde deny_unknown_fields
equivalent) by `from_dict`.
"""

from __future__ import annotations

import dataclasses
import enum
import functools
import typing
from dataclasses import dataclass, field
from typing import Any, Optional

from horaedb_tpu.common import Error, ReadableDuration, ReadableSize, ensure


class UpdateMode(enum.Enum):
    """Row-merge semantics for duplicate primary keys (ref: config.rs:166-172).

    OVERWRITE keeps the row with the highest sequence (LastValueOperator);
    APPEND concatenates binary value columns (BytesMergeOperator).
    """

    OVERWRITE = "Overwrite"
    APPEND = "Append"


class CompressionCodec(enum.Enum):
    UNCOMPRESSED = "uncompressed"
    SNAPPY = "snappy"
    ZSTD = "zstd"
    LZ4 = "lz4"
    GZIP = "gzip"


@dataclass
class SchedulerConfig:
    """Compaction scheduler knobs (ref: config.rs:24-50)."""

    schedule_interval: ReadableDuration = field(
        default_factory=lambda: ReadableDuration.from_secs(10))
    max_pending_compaction_tasks: int = 10
    # Executor memory gate (ref: executor.rs:93-114 uses 2 GiB default).
    memory_limit: ReadableSize = field(default_factory=lambda: ReadableSize.gb(2))
    # Picker thresholds (ref: picker.rs defaults).
    max_record_batch_size: int = 8192
    input_sst_max_num: int = 30
    input_sst_min_num: int = 5
    new_sst_max_size: ReadableSize = field(default_factory=lambda: ReadableSize.gb(1))
    ttl: Optional[ReadableDuration] = None


@dataclass
class ColumnOptions:
    """Per-column parquet writer overrides (ref: config.rs:96-103)."""

    enable_dict: Optional[bool] = None
    enable_bloom_filter: Optional[bool] = None
    encoding: Optional[str] = None
    compression: Optional[CompressionCodec] = None


@dataclass
class WriteConfig:
    """Parquet writer properties (ref: config.rs:105-133)."""

    max_row_group_size: int = 8192
    write_batch_size: int = 1024
    enable_sorting_columns: bool = True
    enable_dict: bool = False
    enable_bloom_filter: bool = False
    encoding: Optional[str] = None
    compression: CompressionCodec = CompressionCodec.SNAPPY
    column_options: dict[str, ColumnOptions] = field(default_factory=dict)
    # persist a device-layout sidecar ({id}.enc) next to each OVERWRITE
    # -mode SST so cold scans skip parquet decode + re-encode entirely
    # (no reference analogue; see storage/sidecar.py)
    enable_sidecar: bool = True
    # compaction outputs above this row count skip the sidecar.  NOTE:
    # unlike the parquet rewrite (streamed, ~MBs of RSS), the sidecar's
    # encoded columns accumulate in RAM until the rewrite finishes —
    # ~12 bytes/row, so the default caps that at ~768 MiB.  Lower it on
    # memory-constrained nodes; large compactions past the cap simply
    # fall back to parquet-only cold reads.
    sidecar_max_rows: int = 64 << 20


@dataclass
class ManifestConfig:
    """Manifest merge thresholds (ref: config.rs:135-155, manifest/mod.rs:48-50)."""

    channel_size: int = 3
    merge_interval: ReadableDuration = field(
        default_factory=lambda: ReadableDuration.from_secs(5))
    min_merge_threshold: int = 10
    hard_merge_threshold: int = 90
    soft_merge_threshold: int = 50
    # how long a writer may throttle waiting for the background fold to
    # drain below the soft threshold before proceeding toward the hard
    # limit (no reference analogue: its merger runs on its own threads)
    soft_merge_max_wait: ReadableDuration = field(
        default_factory=lambda: ReadableDuration.from_secs(2))


@dataclass
class RetryConfig:
    """Object-store retry middleware for the manifest plane (no
    reference analogue — see objstore/middleware.py).  This is the ONE
    engine-level retry layer: the S3 backend keeps its protocol-level
    retries, and the data plane (SST puts/reads) stays single-shot so
    write-path failures surface to the caller's rollback discipline."""

    enabled: bool = True
    max_retries: int = 2
    base_backoff: ReadableDuration = field(
        default_factory=lambda: ReadableDuration.from_millis(50))
    max_backoff: ReadableDuration = field(
        default_factory=lambda: ReadableDuration.from_secs(2))
    # total per-op wall clock including retries; None = unbounded
    op_deadline: Optional[ReadableDuration] = None
    # shared retry token bucket: capacity + refill rate (tokens/second)
    budget: int = 32
    budget_refill_per_s: float = 4.0


@dataclass
class ScrubConfig:
    """Orphan scrubber (storage/gc.py): reconciles data/ objects against
    the manifest and deletes unreferenced objects that stay orphaned for
    a full grace period.  The grace period must comfortably exceed the
    longest plausible gap between an SST put and its manifest add (a
    write or compaction in flight) — minutes, not seconds."""

    enabled: bool = True
    interval: ReadableDuration = field(
        default_factory=lambda: ReadableDuration.from_secs(600))
    grace_period: ReadableDuration = field(
        default_factory=lambda: ReadableDuration.from_secs(600))


@dataclass
class ScanCacheConfig:
    """Tier-2 scan cache: host-RAM per-SST encoded sidecar parts under
    the HBM windows cache (see storage/encoded_cache.py).  An HBM miss
    rebuilds windows from host memory, and a flush/compaction
    invalidates nothing but the SSTs it actually removed — steady
    writes no longer re-cliff reads."""

    # host-RAM byte budget for per-SST encoded parts (0 disables tier 2
    # entirely: every HBM miss re-reads the object store, the
    # pre-tiering behavior)
    tier2_max_bytes: int = 256 << 20
    # write-through admission: the WAL flusher and the compactor insert
    # freshly-encoded parts at write time, so a query landing right
    # after a flush never touches the object store
    write_through: bool = True


@dataclass
class ScanCombineConfig:
    """Aggregate combine/finalize knobs ([scan.combine]; see
    storage/combine.py).  `mode = "sparse"` (default) folds partial
    grids straight into the final output buffers as per-series bucket
    runs and materializes only the requested aggregates — top-k
    queries never build the full groups x buckets grid.  `"dense"`
    reproduces the pre-sparse fold exactly (the bit-identity control
    the chaos suite compares against)."""

    mode: str = "sparse"
    # byte budget for the delta-summation memo: per-segment aggregate
    # partials keyed by the segment's exact SST set, served to
    # narrowed/refined ranges of the same dashboard query shape so only
    # delta segments recompute.  0 disables the memo entirely.
    memo_max_bytes: int = 128 << 20


@dataclass
class ScanDecodeConfig:
    """Device-native decode ([scan.decode]; see ops/device_decode.py):
    eligible aggregate scans upload a segment's ENCODED sidecar buffers
    raw and fuse dict-decode + leaf filter + merge-dedup +
    bucket-aggregate into one jitted device dispatch, so host CPU
    touches the bytes only to move them (ROADMAP item 2).

    mode:
      "auto"   — engage on accelerator backends for plans the fused
                 aggregate declines anyway (the oversized/cold shape);
                 never on XLA-CPU, where host numpy decode measured
                 faster (the host_agg trade).
      "device" — force the fused dispatch wherever structurally
                 eligible (bench A/Bs and the chaos suite's device leg;
                 takes precedence over the fused aggregate).
      "host"   — the pre-change host decode everywhere: THE bit
                 -identity control (the seeded chaos suite
                 byte-compares the two).
    HORAEDB_DEVICE_DECODE=1/0 forces device/host over the config.
    Structurally-ineligible plans/segments fall back per reason to
    scan_decode_fallback_total{reason=} (docs/observability.md)."""

    mode: str = "auto"
    # HBM admission per segment dispatch: a segment whose padded upload
    # would exceed this decodes on host instead (reason="budget")
    max_upload_bytes: int = 256 << 20


@dataclass
class ScanPipelineConfig:
    """Cold-scan pipelining ([scan.pipeline]): the cold read path runs
    as a bounded producer/consumer pipeline — a fetch stage that keeps
    up to `depth` segments' store reads in flight (tier-2-resident
    parts skip the store entirely), a decode/merge stage on the CPU
    pool, and the device stage consuming finished windows — instead of
    phase-at-a-time per segment.  `enabled = false` reproduces the
    pre-pipeline sequential path exactly (results are bit-identical
    either way; the seeded chaos suite asserts it)."""

    enabled: bool = True
    # segments in flight across the whole pipeline (fetch started ->
    # consumed); replaces [scan] prefetch_segments when enabled.  On a
    # 25 ms-latency object store every unit of depth hides another
    # segment's round trips behind the current segment's decode.
    depth: int = 32
    # host-RAM byte budget for in-flight pipeline state (fetched
    # encoded parts/tables + decoded-but-unconsumed windows).  A slow
    # device stage backpressures fetch/decode here instead of
    # ballooning RAM; one oversized segment is still always admitted
    # (progress over the soft bound).
    inflight_bytes: int = 256 << 20


@dataclass
class ScanMeshConfig:
    """In-region 2-D device mesh for the aggregate scan ([scan.mesh];
    parallel/mesh.py, docs/parallel.md): plan segments shard along the
    `time` axis (one merge window per slot, plan-order admission),
    group/tsid blocks along the `series` axis, with an on-mesh
    segmented-reduction combine so a segment-run's windows fold on the
    mesh and only per-run (and, for top-k, per-winner) grids leave a
    chip.  `enabled = false` (default) reproduces the single-chip path
    exactly — THE bit-identity control the seeded chaos suite compares
    against (tests/test_mesh_scan.py)."""

    enabled: bool = False
    # axis sizes; 0 = auto (all local devices, factored by
    # parallel.mesh.default_scan_shape).  `series` must be a power of
    # two — it must divide the padded group space.
    time: int = 0
    series: int = 0
    # per-device admission gate for one round's transient partial grid
    # (g_pad x width x aggs x 4B): rounds that would exceed it fall
    # back to the single-chip kernel (reason="grid_budget").  Pure
    # admission bound, no resident bytes — the sliced per-shard state
    # is 1/series of it and freed when the round's parts download.
    max_grid_bytes: int = 256 << 20


@dataclass
class ScanConfig:
    """Device scan execution knobs (no reference analogue — the TPU
    build's HBM-budget control, SURVEY.md hard part #5)."""

    # max rows per compiled device window; segments larger than this are
    # processed as PK-range-partitioned windows
    max_window_rows: int = 1 << 20
    # HBM-resident post-merge cache budget in rows (0 disables); keyed by
    # (segment, SST set, columns) so writes/compaction invalidate
    # structurally.  The cache accounts BYTES (column widths + memo
    # allowance); this row knob converts at _CACHE_BYTES_PER_ROW unless
    # cache_max_bytes overrides it.
    cache_max_rows: int = 4 << 20
    # explicit budget in bytes for the scan cache (0 = derive from
    # cache_max_rows).  Under the default host_perm merge, cached scan
    # windows are HOST-resident (RAM) and the flush-stack cache — the
    # stacked aggregation inputs actually living in HBM — gets the same
    # budget; worst-case HBM is 1x this value (2x in the device_sort
    # A/B mode, where windows also occupy HBM).
    cache_max_bytes: int = 0
    # devices for the multi-chip aggregate path (0 = single-device);
    # windows batch onto a 1-D segment mesh in rounds of this size with
    # partial grids combined via ICI psum/pmin/pmax
    mesh_devices: int = 0
    # single-device aggregate rounds: windows (across segments) batched
    # into one compiled program per round — the UnionExec axis as a vmap.
    # Meshed scans use mesh_devices as the round size instead.
    agg_batch_windows: int = 16
    # segments whose manifest row count exceeds this stream window-by-
    # window: a first pass over one PK column plans value-range windows,
    # then each window's rows are read via parquet predicate pushdown,
    # so host materialization is bounded by the window budget instead of
    # the segment size (the reference's pull-streaming, read.rs:346-385).
    # 0 disables streaming entirely (always read whole segments).
    stream_read_min_rows: int = 8 << 20
    # byte twin of the row knob (manifest SST sizes): a segment UNDER
    # the row threshold still streams when its stored bytes exceed this
    # — row counts under-estimate host RAM for wide schemas.  Only
    # consulted when streaming is enabled (stream_read_min_rows > 0)
    # and the segment spans more than one window; 0 disables the byte
    # trigger.
    stream_read_min_bytes: int = 512 << 20
    # read device-layout sidecars ({id}.enc) on OVERWRITE-mode bulk
    # segment reads when present (see storage/sidecar.py); disable to
    # force the parquet decode path
    use_sidecar: bool = True
    # segment tables/parts held in memory ahead of the merge position:
    # deeper prefetch overlaps more object-store reads with device work
    # on true-cold scans, at the cost of host RAM for the in-flight
    # segments
    prefetch_segments: int = 4
    # width of the "sst" decode pool (parquet/sidecar deserialize,
    # window prep); 0 = threads.sst_thread_num.  A [scan]-level
    # override so cold-path tuning lives next to prefetch_segments.
    decode_workers: int = 0
    # tiered scan-cache knobs ([scan.cache])
    cache: ScanCacheConfig = field(default_factory=ScanCacheConfig)
    # aggregate combine/finalize knobs ([scan.combine]): sparse-vs-dense
    # fold mode and the delta-summation parts memo budget
    combine: ScanCombineConfig = field(default_factory=ScanCombineConfig)
    # cold-scan pipelining knobs ([scan.pipeline]); when enabled the
    # pipeline's depth/inflight_bytes supersede prefetch_segments on
    # the cold path (the off path keeps using prefetch_segments)
    pipeline: ScanPipelineConfig = field(
        default_factory=ScanPipelineConfig)
    # device-native decode knobs ([scan.decode]): fuse sidecar decode +
    # filter + bucket-aggregate into one device dispatch for eligible
    # aggregate scans; "host" reproduces the pre-change path exactly
    decode: ScanDecodeConfig = field(default_factory=ScanDecodeConfig)
    # 2-D (time x series) mesh scan knobs ([scan.mesh]); mutually
    # exclusive with the legacy 1-D mesh_devices knob above
    mesh: ScanMeshConfig = field(default_factory=ScanMeshConfig)


@dataclass
class ThreadsConfig:
    """Worker-pool sizes (ref: the server's threads config feeding
    StorageRuntimes, src/server/src/main.rs:104-109)."""

    sst_thread_num: int = 4
    compact_thread_num: int = 2
    manifest_thread_num: int = 1


@dataclass
class StorageConfig:
    """Top-level engine config (ref: config.rs:157-164)."""

    write: WriteConfig = field(default_factory=WriteConfig)
    manifest: ManifestConfig = field(default_factory=ManifestConfig)
    scheduler: SchedulerConfig = field(default_factory=SchedulerConfig)
    scan: ScanConfig = field(default_factory=ScanConfig)
    threads: ThreadsConfig = field(default_factory=ThreadsConfig)
    retry: RetryConfig = field(default_factory=RetryConfig)
    scrub: ScrubConfig = field(default_factory=ScrubConfig)
    update_mode: UpdateMode = UpdateMode.OVERWRITE


_DURATION_FIELDS = {"schedule_interval", "merge_interval", "ttl",
                    "soft_merge_max_wait", "base_backoff", "max_backoff",
                    "op_deadline", "interval", "grace_period"}
_SIZE_FIELDS = {"memory_limit", "new_sst_max_size"}
# Nested sections, keyed by field name.  This dict is THE mechanism for
# nested coercion: add new nested config dataclasses here.
_NESTED = {
    "write": WriteConfig,
    "manifest": ManifestConfig,
    "scheduler": SchedulerConfig,
    "scan": ScanConfig,
    "cache": ScanCacheConfig,
    "combine": ScanCombineConfig,
    "pipeline": ScanPipelineConfig,
    "decode": ScanDecodeConfig,
    "mesh": ScanMeshConfig,
    "threads": ThreadsConfig,
    "retry": RetryConfig,
    "scrub": ScrubConfig,
}


def _coerce(cls: type, f: dataclasses.Field, value: Any) -> Any:
    where = f"{cls.__name__}.{f.name}"
    if value is None:
        return None
    if f.name in _DURATION_FIELDS:
        if isinstance(value, ReadableDuration):
            return value
        ensure(isinstance(value, str), f'{where} expects a duration string like "10s"')
        return ReadableDuration.parse(value)
    if f.name in _SIZE_FIELDS:
        if isinstance(value, ReadableSize):
            return value
        ensure(isinstance(value, str), f'{where} expects a size string like "2GB"')
        return ReadableSize.parse(value)
    if f.name == "update_mode":
        if isinstance(value, UpdateMode):
            return value
        try:
            return UpdateMode(value)
        except ValueError as e:
            raise Error.context(
                f"{where}: expected one of {[m.value for m in UpdateMode]}", e)
    if f.name == "compression":
        if isinstance(value, CompressionCodec):
            return value
        try:
            return CompressionCodec(str(value).lower())
        except ValueError as e:
            raise Error.context(
                f"{where}: expected one of {[c.value for c in CompressionCodec]}", e)
    if f.name == "column_options":
        ensure(isinstance(value, dict), f"{where} expects a table of column options")
        return {k: from_dict(ColumnOptions, v) for k, v in value.items()}
    if f.name in _NESTED:
        ensure(isinstance(value, dict), f"{where} expects a config table")
        return from_dict(_NESTED[f.name], value)
    return _check_scalar(cls, f, value, where)


def _check_scalar(cls: type, f: dataclasses.Field, value: Any, where: str) -> Any:
    """Validate plain int/bool/str fields against their declared type so
    misconfigurations fail at load, not mid-flight (bool checked before int
    since bool subclasses int)."""
    hints = _type_hints(cls)
    declared = hints.get(f.name)
    if declared is None:
        return value
    origin = typing.get_origin(declared)
    if origin is typing.Union:  # Optional[T]
        args = [a for a in typing.get_args(declared) if a is not type(None)]
        if len(args) != 1:
            return value
        declared = args[0]
    if declared is bool:
        ensure(isinstance(value, bool), f"{where} expects a boolean")
    elif declared is int:
        ensure(isinstance(value, int) and not isinstance(value, bool),
               f"{where} expects an integer")
    elif declared is str:
        ensure(isinstance(value, str), f"{where} expects a string")
    return value


@functools.lru_cache(maxsize=None)
def _type_hints(cls: type) -> dict[str, Any]:
    return typing.get_type_hints(cls)


def from_dict(cls: type, data: dict[str, Any]) -> Any:
    """Build a config dataclass from a parsed TOML/JSON dict.

    Rejects unknown keys, mirroring serde's deny_unknown_fields
    (ref: config.rs:24-26 and every config struct), and validates value
    types at load time so misconfigurations fail here, not mid-flight.
    """
    ensure(isinstance(data, dict), f"{cls.__name__} config must be a table")
    names = {f.name: f for f in dataclasses.fields(cls)}
    unknown = set(data) - set(names)
    if unknown:
        raise Error(f"unknown config keys for {cls.__name__}: {sorted(unknown)}")
    kwargs = {key: _coerce(cls, names[key], value) for key, value in data.items()}
    return cls(**kwargs)
