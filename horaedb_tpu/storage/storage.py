"""TimeMergeStorage facade (ref: src/storage/src/storage.rs).

`CloudObjectStorage` splits data into `segment_duration` time segments.
write() sorts a batch by PK, stamps builtin columns with the file id as
sequence, writes one Parquet SST, and records it in the manifest
(ref: storage.rs:188-224, 306-332).  scan() groups manifest hits by
segment and executes one device merge-dedup program per segment
(ref: storage.rs:334-369 + our read.py).  On-disk layout matches the
reference (storage.rs:125-135):

    {root_path}/manifest/snapshot
    {root_path}/manifest/delta/{id}
    {root_path}/data/{id}.sst
"""

from __future__ import annotations

import abc
import asyncio
import time
from dataclasses import dataclass
from typing import AsyncIterator, Optional

import pyarrow as pa
import pyarrow.compute as pc

import logging

from horaedb_tpu.common.error import ensure
from horaedb_tpu.objstore import (
    NotFoundError,
    ObjectStore,
    RetryingObjectStore,
    RetryPolicy,
)
from horaedb_tpu.storage import parquet_io, sidecar
from horaedb_tpu.storage.gc import Scrubber, ScrubReport
from horaedb_tpu.storage.config import StorageConfig, UpdateMode
from horaedb_tpu.storage.manifest import Manifest
from horaedb_tpu.storage.read import ParquetReader, ScanPlan, ScanRequest
from horaedb_tpu.storage.sst import FileMeta, SstFile, sst_path
from horaedb_tpu.storage.types import (
    StorageSchema,
    TimeRange,
    Timestamp,
)
from horaedb_tpu.utils import registry

logger = logging.getLogger(__name__)

_WRITE_LATENCY = registry.histogram(
    "storage_write_seconds", "write path latency")
_ROWS_WRITTEN = registry.counter(
    "storage_rows_written_total", "rows written")


@dataclass
class WriteRequest:
    """(ref: storage.rs:58-63)"""

    batch: pa.RecordBatch  # user schema (no builtin columns)
    time_range: TimeRange
    # When false, the caller guarantees the batch does not cross a segment
    # boundary (the load generator path).
    enable_check: bool = True


@dataclass
class WriteResult:
    id: int
    seq: int
    size: int


class TimeMergeStorage(abc.ABC):
    """Engine facade (ref: storage.rs:76-89)."""

    @abc.abstractmethod
    def schema(self) -> StorageSchema: ...

    @abc.abstractmethod
    async def write(self, req: WriteRequest) -> WriteResult: ...

    @abc.abstractmethod
    def scan(self, req: ScanRequest) -> AsyncIterator[pa.RecordBatch]: ...

    @abc.abstractmethod
    async def compact(self) -> None: ...


class CloudObjectStorage(TimeMergeStorage):
    def __init__(self, root_path: str, segment_duration_ms: int,
                 store: ObjectStore, user_schema: pa.Schema,
                 num_primary_keys: int, config: Optional[StorageConfig] = None,
                 runtimes=None):
        from horaedb_tpu.common import runtimes as runtimes_mod

        config = config or StorageConfig()
        self.root_path = root_path.rstrip("/")
        self.segment_duration_ms = segment_duration_ms
        self.store = store
        self.config = config
        self._schema = StorageSchema.try_new(user_schema, num_primary_keys,
                                             config.update_mode)
        self.manifest: Optional[Manifest] = None
        self.scrubber: Optional[Scrubber] = None
        # dedicated worker pools (ref: StorageRuntimes, storage.rs:91-104);
        # shared when a parent (e.g. MetricEngine) passes its own
        self._own_runtimes = runtimes is None
        self.runtimes = runtimes or runtimes_mod.from_config(
            config.threads, sst_override=config.scan.decode_workers)
        self.reader = ParquetReader(store, self.root_path, self._schema,
                                    config, segment_duration_ms,
                                    runtimes=self.runtimes)
        self.compact_scheduler = None  # populated by open()

    @classmethod
    async def open(cls, *args, **kwargs) -> "CloudObjectStorage":
        self = cls(*args, **kwargs)
        # The manifest plane gets the engine's ONE retry layer: a single
        # transient store error must not fail an otherwise-healthy
        # acknowledged write on backends without built-in retries.  The
        # data plane stays single-shot — SST put failures surface to the
        # write path's rollback discipline (and its tests).
        manifest_store: ObjectStore = self.store
        rc = self.config.retry
        if rc.enabled:
            manifest_store = RetryingObjectStore(self.store, RetryPolicy(
                max_retries=rc.max_retries,
                base_backoff_s=rc.base_backoff.seconds,
                max_backoff_s=rc.max_backoff.seconds,
                op_deadline_s=(rc.op_deadline.seconds
                               if rc.op_deadline else None),
                budget=float(rc.budget),
                budget_refill_per_s=rc.budget_refill_per_s))
        self.manifest = await Manifest.open(self.root_path, manifest_store,
                                            self.config.manifest,
                                            runtimes=self.runtimes)
        # the scrubber reconciles against the RAW store: its deletes are
        # already a retry loop (next pass), and reads that fail simply
        # postpone reclamation
        self.scrubber = Scrubber(self.root_path, self.store, self.manifest,
                                 self.config.scrub.grace_period.seconds)
        self.reader.resolve_segment_ssts = self._segment_ssts_now
        await self._start_compaction()
        return self

    async def scrub(self, grace_override_s: Optional[float] = None
                    ) -> ScrubReport:
        """One orphan-reconcile pass (see storage/gc.py); also the
        POST /admin/scrub entry point."""
        ensure(self.scrubber is not None, "storage not opened")
        return await self.scrubber.scrub(grace_override_s=grace_override_s)

    async def _segment_ssts_now(self, segment_start: int,
                                scan_range: Optional[TimeRange]):
        """CURRENT SSTs of one segment that overlap the scan's requested
        range — a streamed segment uses this to survive a compaction
        race mid-segment (read.py).  The range filter mirrors
        build_scan_plan's manifest.find_ssts so recovery cannot leak
        rows from SSTs the original plan excluded."""
        from horaedb_tpu.storage.sst import segment_of

        ssts = await self.manifest.all_ssts()
        return [f for f in ssts
                if segment_of(f, self.segment_duration_ms) == segment_start
                and (scan_range is None
                     or f.meta.time_range.overlaps(scan_range))]

    async def _start_compaction(self) -> None:
        from horaedb_tpu.storage.compaction import Scheduler

        self.compact_scheduler = Scheduler(self)
        await self.compact_scheduler.start()

    async def close(self) -> None:
        if self.compact_scheduler is not None:
            await self.compact_scheduler.stop()
        if self.manifest is not None:
            await self.manifest.close()
        # release EVERY reader-owned cache tier (and the process-wide
        # byte gauges + ledger accounts behind them): a closed table's
        # entries can never be read again, and /debug/memory must not
        # serve phantom tables
        self.reader.close()
        if self._own_runtimes:
            self.runtimes.close()

    # ------------------------------------------------------------------

    def schema(self) -> StorageSchema:
        return self._schema

    def _sort_batch(self, batch: pa.RecordBatch) -> pa.RecordBatch:
        """Sort by primary keys ascending (ref: storage.rs:243-255 does
        this via a DataFusion SortExec; arrow-native sort here)."""
        keys = [(n, "ascending") for n in self._schema.primary_key_names]
        return batch.take(pc.sort_indices(batch, sort_keys=keys))

    def validate_write(self, req: WriteRequest) -> None:
        """All write-path invariants, split out so the WAL ingest front
        end (wal/ingest.py) rejects a bad batch BEFORE logging it."""
        ensure(self.manifest is not None, "storage not opened")
        ensure(req.batch.schema.equals(self._schema.user_schema),
               "write batch schema mismatch")
        # Nulls are rejected at write time: the device scan path carries no
        # null mask, so a null-bearing SST would poison every later scan
        # and compaction of its segment.
        for name, col in zip(req.batch.schema.names, req.batch.columns):
            ensure(col.null_count == 0,
                   f"write batch column {name!r} contains nulls")
        if req.enable_check:
            start_seg = req.time_range.start.truncate_by(self.segment_duration_ms)
            end_seg = Timestamp(int(req.time_range.end) - 1).truncate_by(
                self.segment_duration_ms)
            ensure(start_seg == end_seg,
                   f"write batch crosses segment boundary: {req.time_range}")

    async def write(self, req: WriteRequest) -> WriteResult:
        self.validate_write(req)
        return await self._write_batch(req)

    async def _write_batch(self, req: WriteRequest) -> WriteResult:
        t0 = time.perf_counter()
        file_id = SstFile.allocate_id()

        def prep():  # sort + builtin stamping are CPU work — off the loop
            sorted_batch = self._sort_batch(req.batch)
            return self._schema.fill_builtin_columns(sorted_batch,
                                                     sequence=file_id)

        stamped = await self.runtimes.run("sst", prep)
        result = await self._persist_stamped(file_id, stamped,
                                             req.time_range)
        _WRITE_LATENCY.observe(time.perf_counter() - t0)
        return result

    async def write_stamped(self, table: pa.Table,
                            time_range: TimeRange,
                            pre_commit=None) -> WriteResult:
        """Memtable-flush write path (wal/ingest.py): rows arrive with
        `__seq__` already filled per row (each entry's original write
        seq).  Seqs are PRESERVED — restamping would let a flush racing
        a concurrent write elevate old rows above a newer seq — so the
        SST is sorted by (PK, __seq__) and dedup keeps working off the
        original write order, exactly like a compaction output (which
        also carries heterogeneous per-row seqs).

        `pre_commit` (an async callable) runs AFTER the SST/sidecar
        puts and immediately before the manifest add — the replication
        fencing seam: the SST upload can take a whole lease TTL, so
        ownership must be revalidated at the publish point, not just
        when the flush started.  A raise leaves an orphan SST object
        but no manifest entry — invisible to every reader.
        """
        ensure(self.manifest is not None, "storage not opened")
        ensure(table.schema.names == self._schema.arrow_schema.names,
               "write_stamped expects the full stamped schema")
        file_id = SstFile.allocate_id()

        def prep():
            keys = [(n, "ascending") for n in self._schema.primary_key_names]
            keys.append((self._schema.arrow_schema.names[self._schema.seq_idx],
                         "ascending"))
            ordered = table.take(pc.sort_indices(table, sort_keys=keys))
            return ordered.combine_chunks().to_batches()[0]

        stamped = await self.runtimes.run("sst", prep)
        return await self._persist_stamped(file_id, stamped, time_range,
                                           pre_commit=pre_commit)

    async def _persist_stamped(self, file_id: int, stamped: pa.RecordBatch,
                               time_range: TimeRange,
                               pre_commit=None) -> WriteResult:
        """THE persist tail shared by the direct write path and the WAL
        flush path (write_stamped): SST put overlapped with the sidecar
        put, which completes BEFORE the manifest add — readers never
        see a manifest-listed SST whose sidecar is still in flight, so
        a sidecar miss is permanent per id (the reader memoizes misses
        on that contract).  max_sequence tracks the file id: the
        snapshot codec reconstructs it as the id anyway."""
        path = sst_path(self.root_path, file_id)
        size, _ = await asyncio.gather(
            parquet_io.write_sst(self.store, path, [stamped],
                                 self.config.write, self._schema,
                                 runtimes=self.runtimes),
            self._write_sidecar(file_id, stamped))
        if pre_commit is not None:
            await pre_commit()
        meta = FileMeta(max_sequence=file_id, num_rows=stamped.num_rows,
                        size=size, time_range=time_range)
        await self.manifest.add_file(file_id, meta)
        _ROWS_WRITTEN.inc(stamped.num_rows)
        return WriteResult(id=file_id, seq=file_id, size=size)

    async def _write_sidecar(self, file_id: int,
                             stamped: pa.RecordBatch) -> None:
        """Best-effort device-layout sidecar next to the SST (see
        storage/sidecar.py): pure cache — any failure is logged and
        swallowed, reads fall back to parquet.  The freshly-encoded
        columns are write-through-admitted into the reader's tier-2
        cache (storage/encoded_cache.py): both the direct write path
        and the WAL flusher land here (_persist_stamped), so a query
        right after a write/flush rebuilds its segment without a single
        object-store read."""
        if (self._schema.update_mode is not UpdateMode.OVERWRITE
                or not self.config.write.enable_sidecar
                or stamped.num_rows > self.config.write.sidecar_max_rows):
            return
        try:
            def build():
                cols = sidecar.encode_columns(stamped)
                if cols is None:
                    return None, None
                return cols, sidecar.serialize(cols, stamped.num_rows)

            cols, data = await self.runtimes.run("sst", build)
            if data is None:
                return
            # admit BEFORE the put: the entry is valid the instant the
            # columns exist (ids are immutable), and the SST only
            # becomes reader-visible after the manifest add anyway
            self.reader.encoded_cache.admit(file_id, cols,
                                            stamped.num_rows)
            await self.store.put(
                sidecar.sidecar_path(self.root_path, file_id), data)
        except Exception as exc:  # noqa: BLE001 — cache write only
            logger.warning("sidecar write failed for sst %s: %s",
                           file_id, exc)

    # Scans race with compaction: the manifest can reference an SST that
    # compaction deletes before the scan's parquet read runs.  The data
    # lives on in the compacted output, so the remedy is a fresh plan for
    # the not-yet-yielded segments (bounded retries).
    _SCAN_RETRIES = 3

    async def scan(self, req: ScanRequest,
                   first_plan: Optional[ScanPlan] = None,
                   keep_builtin: bool = False,
                   segment_filter=None) -> AsyncIterator[pa.RecordBatch]:
        # explicit aclose on abandonment: an `async for` left mid-loop
        # does NOT close its source, and GC-time finalization would let
        # the scan pipeline's in-flight tasks outlive the query into
        # table teardown (deterministic-teardown discipline, PR 3/8)
        seg_iter = self.scan_segments(req, first_plan=first_plan,
                                      keep_builtin=keep_builtin,
                                      segment_filter=segment_filter)
        try:
            async for _seg, batch in seg_iter:
                if batch is not None:
                    yield batch
        finally:
            await seg_iter.aclose()

    async def scan_segments(self, req: ScanRequest,
                            first_plan: Optional[ScanPlan] = None,
                            keep_builtin: bool = False,
                            segment_filter=None):
        """scan() with segment attribution: yields (segment_start,
        batch) parts plus a (segment_start, None) completion marker per
        segment — the hybrid WAL scan (wal/ingest.py) overlays memtable
        rows per segment and needs to know when one is complete.
        `segment_filter(segment_start) -> bool` restricts the scan to a
        stable subset across compaction-race replans."""
        done: set[int] = set()
        for attempt in range(self._SCAN_RETRIES + 1):
            # attempt 0 may reuse a caller-built plan (plan_query):
            # one manifest lookup per query; a stale plan just races
            # into the NotFoundError replan below like any other scan
            plan = (first_plan if attempt == 0 and first_plan is not None
                    else await self.build_scan_plan(
                        req, keep_builtin=keep_builtin))
            plan.segments = [s for s in plan.segments
                             if s.segment_start not in done
                             and (segment_filter is None
                                  or segment_filter(s.segment_start))]
            exec_iter = self.reader.execute_segments(plan)
            try:
                async for seg_start, batch in exec_iter:
                    if batch is None:
                        # explicit completion marker: only now is the
                        # segment retry-safe to skip (it may have
                        # spanned several window batches)
                        done.add(seg_start)
                    yield seg_start, batch
                return
            except NotFoundError:
                if attempt == self._SCAN_RETRIES:
                    raise
                logger.info("scan raced a compaction (sst vanished); "
                            "replanning remaining segments")
            finally:
                # deterministic teardown on abandonment/error: drain
                # the read pipeline NOW, not at GC finalization
                await exec_iter.aclose()

    async def scan_aggregate(self, req: ScanRequest, spec,
                             first_plan: Optional[ScanPlan] = None,
                             top_k=None):
        """Downsample pushdown: merge + GROUP BY group_col, time(bucket)
        on device; returns (group_values, grids).  See read.AggregateSpec.
        The fused path (single-device host_perm) accumulates into one
        query-global device grid and restarts whole on a compaction
        race; the parts path skips segments completed before the race
        on its replan.

        `top_k` (a plan.TopKSpec) pushes the ranking into the combine:
        the parts path folds per-group spans into a bounded score pass
        and materializes only the k winners (combine_top_k) — the full
        groups x buckets grid is never built.  The fused path's grids
        already live on device, so it keeps the host-side slice."""
        if first_plan is None:
            first_plan = await self.build_scan_plan(req)
        # per-trace memory attribution (common/memledger.py): a cold
        # aggregate moves megabytes into the cache tiers — the trace
        # records which account they landed in
        mem_marks = self.reader._mem_delta_marks()
        try:
            if (self.reader.fused_aggregate_ok(first_plan)
                    and not self.reader.router_covers(first_plan)):
                from horaedb_tpu.storage.plan import apply_top_k

                counted: set = set()  # ops metrics survive restarts
                plan = first_plan
                for attempt in range(self._SCAN_RETRIES + 1):
                    try:
                        values, grids = \
                            await self.reader.execute_aggregate_fused(
                                plan, spec, counted=counted)
                        if top_k is not None:
                            values, grids = apply_top_k(values, grids,
                                                        top_k)
                        return values, grids
                    except NotFoundError:
                        if attempt == self._SCAN_RETRIES:
                            raise
                        logger.info("fused aggregate raced a compaction; "
                                    "restarting")
                        plan = await self.build_scan_plan(req)
            done: dict[int, list] = {}
            for attempt in range(self._SCAN_RETRIES + 1):
                # attempt 0 reuses the plan built for the fused gate —
                # one manifest lookup per query, not two
                plan = first_plan if attempt == 0 \
                    else await self.build_scan_plan(req)
                plan.segments = [s for s in plan.segments
                                 if s.segment_start not in done]
                try:
                    async for seg_start, parts in \
                            self.reader.aggregate_segments(
                                plan, spec, top_k=top_k):
                        done[seg_start] = parts
                    break
                except NotFoundError:
                    if attempt == self._SCAN_RETRIES:
                        raise
                    logger.info("aggregate scan raced a compaction; "
                                "replanning")
            all_parts = [p for seg in sorted(done) for p in done[seg]]
            return self.reader.finalize_aggregate(all_parts, spec,
                                                  top_k=top_k)
        finally:
            self.reader._mem_delta_attribute(mem_marks)

    async def build_scan_plan(self, req: ScanRequest,
                              keep_builtin: bool = False) -> ScanPlan:
        ensure(self.manifest is not None, "storage not opened")
        ssts = await self.manifest.find_ssts(req.range)
        return self.reader.build_plan(ssts, req, keep_builtin=keep_builtin)

    async def plan_query(self, req: ScanRequest, spec=None, top_k=None):
        """Build the composable QueryPlan every query shape routes
        through (see storage/plan.py): scan -> aggregate? -> top_k?."""
        from horaedb_tpu.storage.plan import QueryPlan

        ensure(spec is not None or top_k is None,
               "top-k requires an aggregate stage")
        scan = await self.build_scan_plan(req)
        return QueryPlan(scan=scan, request=req, aggregate=spec,
                         top_k=top_k)

    def execute_plan(self, qp):
        """Execute a QueryPlan.  Row-scan plans return the async batch
        iterator; aggregate plans return an awaitable of
        (group_values, grids).  A top-k stage is pushed down into the
        combine (scan_aggregate top_k=) so the parts path never builds
        the full groups x buckets grid.  The plan built by plan_query
        is the first attempt's scan plan — one manifest lookup per
        query, not two."""
        if qp.aggregate is None:
            return self.scan(qp.request, first_plan=qp.scan)
        return self.scan_aggregate(qp.request, qp.aggregate,
                                   first_plan=qp.scan, top_k=qp.top_k)

    async def compact(self) -> None:
        if self.compact_scheduler is not None:
            await self.compact_scheduler.trigger()

    @property
    def value_idxes(self) -> list[int]:
        return self._schema.value_idxes
