"""Merge operators: row-merge semantics for equal primary keys
(ref: src/storage/src/operator.rs).

The reference applies an operator to each PK group as it streams by.  Here
the Overwrite path (LastValue) runs entirely on device inside
ops.merge.merge_dedup_last, so this module provides:

- the host-side reference implementations used for testing and for the
  Append path (BytesMerge concatenates variable-length Binary values,
  which stays on host per the fixed-width device design —
  SURVEY.md hard part #4);
- group-wise application over a sorted Arrow batch via vectorized numpy
  run detection (no per-row Python loop).
"""

from __future__ import annotations

import numpy as np
import pyarrow as pa

from horaedb_tpu import native
from horaedb_tpu.common.error import Error, ensure


def _run_starts_host(batch: pa.RecordBatch, pk_indices: list[int]) -> np.ndarray:
    """Boolean run-start mask over a PK-sorted batch (host twin of
    ops.merge.sorted_run_starts).  pk_indices are explicit because a
    projection may have reordered columns — PKs are NOT necessarily the
    first columns of the batch.

    Integer key columns go through the C++ kernel (native/); string and
    other types fall back to numpy object comparison.
    """
    n = batch.num_rows
    if n == 0:
        return np.zeros(0, dtype=bool)
    int_cols: list[np.ndarray] = []
    other_cols: list[np.ndarray] = []
    for i in pk_indices:
        col = batch.column(i).to_numpy(zero_copy_only=False)
        if np.issubdtype(col.dtype, np.integer):
            int_cols.append(col.astype(np.int64, copy=False))
        else:
            other_cols.append(col)
    if int_cols:
        starts = native.run_starts_i64(int_cols)
    else:
        starts = np.zeros(n, dtype=bool)
        starts[0] = True
    for col in other_cols:
        starts[1:] |= col[1:] != col[:-1]
    return starts


class LastValueOperator:
    """Keep the last row of each group — highest sequence wins
    (ref: operator.rs:37-44).  Overwrite mode."""

    def merge_sorted_batch(self, batch: pa.RecordBatch,
                           pk_indices: list[int]) -> pa.RecordBatch:
        n = batch.num_rows
        if n == 0:
            return batch
        starts = _run_starts_host(batch, pk_indices)
        last_idx = native.run_last_indices(starts)
        return batch.take(pa.array(last_idx))


class BytesMergeOperator:
    """Concatenate Binary value columns across each group, in sequence
    order; non-value columns keep the group's first row
    (ref: operator.rs:46-111).  Append mode."""

    def __init__(self, value_idxes: list[int]):
        self.value_idxes = value_idxes

    def merge_sorted_batch(self, batch: pa.RecordBatch,
                           pk_indices: list[int]) -> pa.RecordBatch:
        n = batch.num_rows
        if n == 0:
            return batch
        for idx in self.value_idxes:
            t = batch.column(idx).type
            ensure(pa.types.is_binary(t) or pa.types.is_large_binary(t),
                   f"BytesMergeOperator requires binary columns, got {t}")

        starts = _run_starts_host(batch, pk_indices)
        first_idx = np.nonzero(starts)[0]
        group_of_row = np.cumsum(starts) - 1
        num_groups = len(first_idx)

        columns = []
        for idx in range(batch.num_columns):
            col = batch.column(idx)
            if idx not in self.value_idxes:
                columns.append(col.take(pa.array(first_idx)))
                continue
            # vectorized ragged concat: per-row byte lengths summed per group
            ensure(col.null_count == 0,
                   "BytesMergeOperator input contains nulls (write path "
                   "rejects nulls; corrupt SST?)")
            arr = col.cast(pa.binary()) if not pa.types.is_binary(col.type) else col
            flat = arr.combine_chunks() if isinstance(arr, pa.ChunkedArray) else arr
            offsets = np.frombuffer(flat.buffers()[1], dtype=np.int32,
                                    count=n + 1, offset=flat.offset * 4)
            row_lens = (offsets[1:] - offsets[:-1]).astype(np.int64)
            group_lens = np.bincount(group_of_row, weights=row_lens,
                                     minlength=num_groups).astype(np.int64)
            values_buf = flat.buffers()[2]
            data = np.frombuffer(values_buf, dtype=np.uint8)[
                offsets[0]: offsets[n]] if values_buf is not None else np.zeros(0, np.uint8)
            new_offsets = np.zeros(num_groups + 1, dtype=np.int32)
            np.cumsum(group_lens, out=new_offsets[1:])
            merged = pa.Array.from_buffers(
                pa.binary(), num_groups,
                [None, pa.py_buffer(new_offsets.tobytes()),
                 pa.py_buffer(data.tobytes())])
            columns.append(merged)
        return pa.RecordBatch.from_arrays(columns, schema=batch.schema)


def build_operator(update_mode, value_idxes: list[int]):
    from horaedb_tpu.storage.config import UpdateMode

    if update_mode is UpdateMode.OVERWRITE:
        return LastValueOperator()
    if update_mode is UpdateMode.APPEND:
        return BytesMergeOperator(value_idxes)
    raise Error(f"unknown update mode: {update_mode}")
