"""Bounded producer/consumer pipeline for the cold scan path.

Phase-at-a-time cold scans leave the device idle while the object
store answers and the store idle while the CPU decodes (the PR 5 stage
profiles made this visible: sidecar reads, encode/merge and device
aggregation execute strictly sequentially per query).  This module
overlaps the three as independent stages with bounded in-flight state:

  fetch   — per-segment store reads (tier-2-resident encoded parts
            skip the store via EncodedSegmentCache's subset-get; only
            missing SSTs cross the wire), up to `depth` segments in
            flight, admitted STRICTLY in plan order so a small depth
            can never hand its last slot to a later segment and
            deadlock the decode position;
  decode  — one segment at a time on the CPU pool (encode + k-way
            merge + window planning fused into one pool dispatch;
            concurrent decodes measured a net loss on low-core hosts,
            see the note in read._cached_windows);
  device  — the consumer (aggregation rounds / row decode), fed
            through an ordered queue.

Backpressure: a `PipelineBudget` bounds both segments in flight
(`depth`) and host bytes held by the pipeline (`inflight_bytes`:
fetched-but-undecoded parts plus decoded-but-unconsumed windows), so a
slow device stage stalls fetch instead of ballooning host RAM.  One
oversized segment is always admitted — progress over the soft bound.

Cancellation/teardown is deterministic: `aclose()` cancels the stage
tasks and AWAITS them.  A pool job already running cannot be
interrupted, so awaiting the cancelled task drains it (the task only
delivers its CancelledError at the next suspension point) — the same
discipline the PR 3 SIGSEGV fix demands: no pool job may outlive the
scan that issued it into engine/table teardown.

`[scan.pipeline] enabled = false` routes scans through the pre-change
pump in read._cached_windows; results are bit-identical either way
(tests/test_pipeline.py asserts it under seeded chaos schedules).  So
does a scan with no store I/O to overlap — every bulk segment tier-2
resident (read._pipeline_has_io): with nothing to hide, the stage
concurrency only inflates the same CPU work on low-core hosts.
"""

from __future__ import annotations

import asyncio
import time
from typing import Optional

from horaedb_tpu.common.deadline import checkpoint as deadline_checkpoint
from horaedb_tpu.utils import registry, trace_add

# pipeline-stage attribution rides the same labeled families as the
# plan stages (docs/observability.md): fetch/decode/device measure the
# PIPELINE's per-stage occupancy (fetch ~= sidecar_read+parquet_read,
# decode ~= encode_merge, device ~= device_aggregate wall including
# pool-queue wait), diffable around a query like any other stage
PIPELINE_STAGES = ("fetch", "decode", "device")
STAGE_SECONDS = {
    s: registry.histogram("scan_stage_seconds",
                          "wall seconds per merge-scan plan stage"
                          ).labels(stage=s)
    for s in PIPELINE_STAGES
}
STAGE_ROWS = {
    s: registry.counter("scan_stage_rows_total",
                        "rows entering each plan stage").labels(stage=s)
    for s in PIPELINE_STAGES
}
STAGE_BYTES = {
    s: registry.counter("scan_stage_bytes_total",
                        "bytes entering each plan stage").labels(stage=s)
    for s in PIPELINE_STAGES
}
_STALLS = {
    s: registry.counter(
        "scan_pipeline_stalls_total",
        "times a pipeline stage waited on its neighbour (stage= is "
        "the stage that STARVED: fetch waits on the in-flight budget, "
        "decode on a store read, device on decode)").labels(stage=s)
    for s in PIPELINE_STAGES
}
_INFLIGHT_BYTES = registry.gauge(
    "scan_pipeline_inflight_bytes",
    "host bytes held in flight by scan pipelines (fetched parts + "
    "decoded windows not yet consumed)")

# memory plane: pipeline in-flight bytes are transient (per-scan
# budgets, exact-through-teardown) with no single resident owner, so
# the process-level account reads the gauge the budgets already keep
# exact — one source of truth, no double entry (common/memledger.py)
from horaedb_tpu.common.memledger import ledger as _memledger  # noqa: E402

_MEM_ACCOUNT = _memledger.register(
    "pipeline_inflight", lambda: int(_INFLIGHT_BYTES.value),
    kind="pipeline_inflight", owner="storage/pipeline")


# mesh-axis stalls live with the pipeline's stall telemetry: the mesh
# dispatcher IS the pipeline's device stage when [scan.mesh] is on —
# its rounds are fed by the same fetch/decode stages, with plan-order
# slot admission per mesh column (read._aggregate_segments_mesh)
MESH_AXES = ("time", "series")
_MESH_STALLS = {
    a: registry.counter(
        "scan_mesh_stalls_total",
        "mesh rounds dispatched with idle shards, per axis: time = "
        "the window feed filled fewer slots than the time axis (tail "
        "rounds or fetch/decode backpressure), series = the round's "
        "group space left whole series blocks empty").labels(axis=a)
    for a in MESH_AXES
}


def stall_counts() -> dict:
    """Cumulative per-stage stall counts (bench/stats snapshots)."""
    return {s: int(c.value) for s, c in _STALLS.items()}


def mesh_stall_counts() -> dict:
    """Cumulative per-axis mesh stall counts (/stats mesh section)."""
    return {a: int(c.value) for a, c in _MESH_STALLS.items()}


def note_stall(stage: str) -> None:
    _STALLS[stage].inc()
    trace_add(f"pipeline_stall_{stage}", 1)


def note_mesh_stall(axis: str) -> None:
    _MESH_STALLS[axis].inc()
    trace_add(f"mesh_stall_{axis}", 1)


def observe_stage(stage: str, seconds: float, rows: int = 0,
                  nbytes: int = 0) -> None:
    STAGE_SECONDS[stage].observe(seconds)
    trace_add(f"stage_{stage}_ms", seconds * 1e3)
    if rows:
        STAGE_ROWS[stage].inc(rows)
        trace_add(f"stage_{stage}_rows", rows)
    if nbytes:
        STAGE_BYTES[stage].inc(nbytes)
        trace_add(f"stage_{stage}_bytes", nbytes)


def windows_nbytes(windows: list) -> int:
    """Host bytes held by a segment's decoded windows (column arrays;
    memo allowances are charged by the scan cache, not here).  A
    device-decoded segment's entry is a finished aggregate partial
    (ops.device_decode.DevicePart) whose host footprint is just its
    downloaded grids."""
    total = 0
    for w in windows:
        cols = getattr(w, "columns", None)
        if cols is None:
            total += int(getattr(w, "nbytes", 0))
        else:
            total += sum(int(c.nbytes) for c in cols.values())
    return total


class PipelineBudget:
    """Slot + byte admission for one scan's pipeline.

    Slots are granted to bulk segments STRICTLY in plan order (each
    caller presents its ticket index): out-of-order grants could hand
    the last slot to segment N+5 while the decode stage waits on
    segment N whose fetch cannot start — a deadlock at small depths.
    Streamed segments take no slot (they bound their own
    materialization window-by-window) and only charge bytes.
    """

    def __init__(self, max_bytes: int, depth: int):
        self.max_bytes = max(1, int(max_bytes))
        self.depth = max(1, int(depth))
        self.slots = 0
        self.bytes = 0
        self.high_water = 0
        self._turn = 0  # next ticket allowed to take a slot
        # one event PER WAITING TICKET: only the head-of-line ticket is
        # ever woken (on turn advance or freed room), so a release
        # costs O(1) — a shared gate woke every parked fetch task on
        # every admit/release, O(N^2) spurious event-loop wakeups per
        # scan competing with decode/device on exactly the low-core
        # hosts where the residual wall is already CPU-bound
        self._waiters: dict[int, asyncio.Event] = {}

    def _has_room(self) -> bool:
        # always admit when nothing is in flight: a single segment
        # larger than the whole budget must still make progress
        if self.slots == 0 and self.bytes == 0:
            return True
        return self.slots < self.depth and self.bytes < self.max_bytes

    def _recheck(self) -> None:
        if self._has_room():
            self._wake_head()

    def _wake_head(self) -> None:
        ev = self._waiters.get(self._turn)
        if ev is not None:
            ev.set()

    async def admit(self, ticket: int, est_bytes: int = 0) -> None:
        """Take a fetch slot; waits while the pipeline is full or an
        earlier ticket has not been admitted yet.  `est_bytes` (the
        manifest-derived segment size estimate) is charged ON
        admission — an in-flight read must count against the budget
        BEFORE its bytes arrive, or N concurrent slow reads would all
        admit against an empty ledger and land together over budget.
        The fetcher swaps the estimate for actual bytes on
        completion."""
        stalled = False
        try:
            while self._turn != ticket or not self._has_room():
                if self._turn == ticket:
                    # only the head-of-line waiter reports
                    # backpressure; later tickets waiting their turn
                    # is not a stall
                    stalled = True
                ev = self._waiters.setdefault(ticket, asyncio.Event())
                ev.clear()
                await ev.wait()
        finally:
            self._waiters.pop(ticket, None)
        if stalled:
            note_stall("fetch")
        self._turn += 1
        self.slots += 1
        self.charge(est_bytes)
        # the NEW head re-evaluates room for itself (loops back to
        # waiting if full; a later release re-wakes it)
        self._wake_head()

    def charge(self, nbytes: int) -> None:
        if nbytes <= 0:
            return
        self.bytes += nbytes
        _INFLIGHT_BYTES.inc(nbytes)
        self.high_water = max(self.high_water, self.bytes)
        self._recheck()

    def release(self, nbytes: int) -> None:
        if nbytes > 0:
            self.bytes -= nbytes
            _INFLIGHT_BYTES.inc(-nbytes)
        self._recheck()

    def consume(self, nbytes: int, took_slot: bool) -> None:
        """The device stage picked a segment up: free its slot+bytes."""
        if took_slot:
            self.slots -= 1
        self.release(nbytes)

    def close(self) -> None:
        """Zero out whatever this pipeline still holds (teardown must
        leave the process-global in-flight gauge exact)."""
        if self.bytes:
            _INFLIGHT_BYTES.inc(-self.bytes)
            self.bytes = 0
        self.slots = 0
        for ev in self._waiters.values():
            ev.set()


class _Item:
    __slots__ = ("seg", "windows", "read_s", "nbytes", "took_slot")

    def __init__(self, seg, windows, read_s, nbytes, took_slot):
        self.seg = seg
        self.windows = windows
        self.read_s = read_s
        self.nbytes = nbytes
        self.took_slot = took_slot


class _Error:
    __slots__ = ("exc",)

    def __init__(self, exc):
        self.exc = exc


_DONE = object()


class ScanPipeline:
    """Owns the fetch and decode stages for one scan's to-read
    segments; read._cached_windows_pipelined is the consumer (yielding
    into the device stage).  Segments are produced in plan order."""

    def __init__(self, reader, plan, segments: list):
        self.reader = reader
        self.plan = plan
        self.segments = segments
        cfg = reader.config.scan.pipeline
        self.budget = PipelineBudget(cfg.inflight_bytes, cfg.depth)
        # unbounded on purpose: depth/bytes admission already bounds
        # how much can ever sit here, and control messages (errors,
        # completion) must never block behind a full queue
        self._queue: asyncio.Queue = asyncio.Queue()
        self._streamed = {id(s) for s in segments
                          if reader._stream_segment(s)}
        self._reads: dict[int, asyncio.Task] = {}
        self._consumed = 0
        self._producer: Optional[asyncio.Task] = None
        # fetch-stage CPU bound: with `depth` reads in flight, letting
        # every one race its deserialize on the shared pool starves the
        # decode/device stages of cores (the PR 4 lesson, re-measured
        # here as tier2-cold 0.74x).  I/O stays `depth`-wide; the
        # CPU-side deserialize/assemble runs at most half-the-cores
        # wide, leaving the other half for decode + device.
        import os

        self._cpu_sem = asyncio.Semaphore(
            max(1, (os.cpu_count() or 4) // 2))
        # a plan that can't use sidecars at all reads whole parquet
        # segments, whose pool-side decodes can't go through the
        # bounded runner (they dispatch inside parquet_io.read_sst) —
        # cap those reads at the pre-change prefetch width instead of
        # `depth`, or 32 in-flight parquet decodes queue ahead of the
        # decode/device stages on the shared pool (the same priority
        # inversion the bounded runner exists for).  Sidecar-capable
        # plans keep full-depth I/O; a per-segment parquet fallback
        # inside one (missing sidecar, negative-memoed) is rare enough
        # not to gate
        self._plan_sidecar_ok = reader._sidecar_plan_ok(plan)
        self._read_sem = asyncio.Semaphore(max(4, os.cpu_count() or 4))
        if segments:
            ticket = 0
            for seg in segments:
                if id(seg) in self._streamed:
                    continue
                self._reads[id(seg)] = asyncio.create_task(
                    self._fetch(seg, ticket))
                ticket += 1
            self._producer = asyncio.create_task(self._produce())

    # ---- fetch stage -------------------------------------------------------

    # admission-time estimate of a segment's in-flight bytes, from the
    # manifest row counts (same rows->bytes conversion as the scan
    # cache's legacy knob, read._CACHE_BYTES_PER_ROW); swapped for the
    # actual fetched size when the read completes
    _EST_BYTES_PER_ROW = 32

    async def _bounded_runner(self, fn, *args):
        async with self._cpu_sem:
            return await self.reader._run_pool(self.plan.pool, fn, *args)

    async def _fetch(self, seg, ticket: int):
        est = sum(f.meta.num_rows
                  for f in seg.ssts) * self._EST_BYTES_PER_ROW
        await self.budget.admit(ticket, est)
        try:
            # stage-boundary checkpoint: an admitted fetch for an
            # expired query must not start its store reads.  INSIDE
            # the try: the admission-time estimate must release on
            # this exit too, or sibling fetches park on a phantom-full
            # budget while the error drains to the consumer
            deadline_checkpoint()
            t0 = time.perf_counter()
            resident = self.reader._resident_segment_parts(seg,
                                                           self.plan)
            if resident is not None:
                # zero store I/O: assemble the tier-2-resident parts
                # here so segment N+1's assemble overlaps segment N's
                # decode+device — but through the BOUNDED runner, so
                # `depth` resident segments can't flood the pool ahead
                # of the decode/device work the consumer is actually
                # waiting on (priority inversion measured as tier2-cold
                # 0.68x vs the sequential pump either way: unbounded
                # fetch-side assemble OR assemble serialized into the
                # decode stage)
                es = await self._bounded_runner(
                    self.reader._assemble_resident_segment, seg,
                    resident, self.plan)
                if es is not None:
                    nbytes = int(es.nbytes)
                    self.budget.charge(nbytes)
                    read_s = time.perf_counter() - t0
                    observe_stage("fetch", read_s, rows=int(es.n),
                                  nbytes=nbytes)
                    return es, read_s, nbytes
                # assembly failed: memoize the composition (the
                # negative memo is event-loop-owned — we are back on
                # the loop here) and take the full fetch path, which
                # now routes to parquet, same as the sequential path
                self.reader.encoded_cache.mark_assembly_failed(
                    frozenset(f.id for f in seg.ssts))
            if self._plan_sidecar_ok:
                table, read_s = await self.reader._read_segment_any(
                    seg, self.plan, runner=self._bounded_runner)
            else:
                async with self._read_sem:
                    table, read_s = await self.reader._read_segment_any(
                        seg, self.plan, runner=self._bounded_runner)
            nbytes = int(table.nbytes)
            self.budget.charge(nbytes)
            observe_stage("fetch", time.perf_counter() - t0,
                          rows=int(table.num_rows), nbytes=nbytes)
        finally:
            self.budget.release(est)
        return table, read_s, nbytes

    # ---- decode stage ------------------------------------------------------

    async def _produce(self) -> None:
        try:
            for seg in self.segments:
                # cooperative cancellation point between segments: an
                # expired deadline stops fetching/decoding a doomed
                # scan (the error flows to the consumer in order)
                deadline_checkpoint()
                if id(seg) in self._streamed:
                    item = await self._decode_streamed(seg)
                else:
                    item = await self._decode_bulk(seg)
                await self._queue.put(item)
            self._queue.put_nowait(_DONE)
        except asyncio.CancelledError:
            raise
        except BaseException as exc:  # noqa: BLE001 — relayed, not handled
            # surfaces to the consumer IN ORDER (all prior segments'
            # items are already queued), preserving the sequential
            # path's error position for the compaction-race replan
            self._queue.put_nowait(_Error(exc))

    async def _decode_bulk(self, seg) -> _Item:
        task = self._reads.pop(id(seg))
        if not task.done():
            note_stall("decode")
        table, read_s, fetch_bytes = await task
        t0 = time.perf_counter()
        if table.num_rows:
            windows = await self.reader._run_pool(
                self.plan.pool, self.reader._decode_segment_windows,
                table, self.plan)
        else:
            windows = []
        del table
        nbytes = windows_nbytes(windows)
        # swap the fetched representation's bytes for the windows'
        self.budget.charge(nbytes)
        self.budget.release(fetch_bytes)
        observe_stage("decode", time.perf_counter() - t0,
                      rows=sum(w.n_valid for w in windows), nbytes=nbytes)
        return _Item(seg, windows, read_s, nbytes, True)

    async def _decode_streamed(self, seg) -> _Item:
        # streamed segments interleave their own fetch+decode window
        # by window (bounded materialization); they take no pipeline
        # slot so later bulk fetches keep overlapping them, and only
        # their finished windows charge the byte budget
        t0 = time.perf_counter()
        dispatched, read_s = await self.reader._read_streamed_dispatched(
            seg, self.plan)
        windows = await self.reader._run_pool(
            self.plan.pool, self.reader._finalize_windows, dispatched)
        nbytes = windows_nbytes(windows)
        self.budget.charge(nbytes)
        observe_stage("decode", time.perf_counter() - t0 - read_s,
                      rows=sum(w.n_valid for w in windows), nbytes=nbytes)
        return _Item(seg, windows, read_s, nbytes, False)

    # ---- consumer API ------------------------------------------------------

    async def next_segment(self):
        """(seg, windows, read_seconds) in plan order; raises the
        producer's error at the exact segment position it occurred."""
        if self._queue.empty() and self._consumed:
            # empty AFTER the first segment is starvation; empty on
            # the first call is just ramp-up (the producer cannot have
            # finished segment 0 yet) and would make every pipelined
            # scan report >= 1 phantom device stall
            note_stall("device")
        item = await self._queue.get()
        self._consumed += 1
        if item is _DONE:
            # consumer asked past the last segment — a caller bug
            raise RuntimeError("scan pipeline exhausted")
        if isinstance(item, _Error):
            raise item.exc
        self.budget.consume(item.nbytes, item.took_slot)
        return item.seg, item.windows, item.read_s

    async def aclose(self) -> None:
        """Deterministic teardown: cancel every stage task and AWAIT
        them — a cancelled task whose pool job is mid-flight only
        finishes after the job does, so nothing this scan dispatched
        outlives it into table/engine teardown."""
        tasks = list(self._reads.values())
        self._reads.clear()
        if self._producer is not None:
            tasks.append(self._producer)
            self._producer = None
        for t in tasks:
            t.cancel()
        if tasks:
            await asyncio.gather(*tasks, return_exceptions=True)
        # record the observed high-water for /stats before zeroing
        hw = self.reader._pipeline_high_water
        self.reader._pipeline_high_water = max(hw, self.budget.high_water)
        self.budget.close()
