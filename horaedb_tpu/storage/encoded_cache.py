"""Tier-2 scan cache: host-RAM per-SST encoded sidecar parts.

The HBM scan cache (storage/scan_cache.py) keys whole segments by their
SST set, so EVERY write or compaction misses the whole segment and
forces a full object-store re-read + re-merge — even when all but one
tiny SST is unchanged (the post-flush cliff).  This cache sits under it
with per-SST granularity:

    tier 1 (HBM)      post-merge windows, key = (segment, SST set, ...)
    tier 2 (host RAM) per-SST EncodedSegment parts, key = immutable SST id
    tier 3 (store)    {id}.enc sidecars / {id}.sst parquet

A tier-1 miss rebuilds windows from tier-2 parts without touching the
object store, and only the SSTs a flush/compaction actually removed
leave tier 2 (`invalidate`) — everything else stays resident.  The WAL
flusher and the compactor hold the freshly-encoded columns in hand at
write time and insert them here (`admit`, write-through), so a query
landing right after a flush reads nothing from the store at all.

Correctness is structural, exactly like tier 1: SST ids are immutable
and never reused, so an entry can never be stale.  Entries hold the
columns of ONE complete SST — block-pruned partial loads are never
admitted (they are row subsets tied to one predicate).

The cache also owns the negative path: SST ids known to lack a usable
sidecar (pre-feature files, failed best-effort writes) are memoized
per id so cold scans skip doomed GETs.  Negative entries are strictly
per-SST — a cross-SST assembly failure must NOT poison its siblings
(see read._read_segment_encoded).

Ownership: event-loop owned, like tier 1 — gets/puts happen on the
reader's loop; the CPU-heavy deserialize runs on worker pools before
insertion.  No lock.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Optional

from horaedb_tpu.utils import registry, trace_add

# tier-labeled children of the shared scan-cache families (the hbm
# tier lives in storage/scan_cache.py); admissions/invalidated are
# tier-2-only concepts but carry the label for a uniform query surface
_HITS = registry.counter(
    "scan_cache_hits_total",
    "scan cache hits by tier").labels(tier="tier2")
_MISSES = registry.counter(
    "scan_cache_misses_total",
    "scan cache misses by tier").labels(tier="tier2")
_EVICTIONS = registry.counter(
    "scan_cache_evictions_total",
    "scan cache evictions by tier").labels(tier="tier2")
_ADMISSIONS = registry.counter(
    "scan_cache_admissions_total",
    "write-through insertions from flush/compaction sidecar builds"
    ).labels(tier="tier2")
_INVALIDATED = registry.counter(
    "scan_cache_invalidated_total",
    "cache entries dropped because their SST was deleted"
    ).labels(tier="tier2")
_BYTES = registry.gauge(
    "scan_cache_bytes",
    "resident cache bytes by tier (host RAM)").labels(tier="tier2")

# negative-entry bound: clear-all on overflow (re-learning a miss costs
# one GET; unbounded growth costs RAM forever)
_MISSING_MAX = 65536


def _base_size(arr) -> Optional[tuple[int, int]]:
    """(id, byte size) of the buffer an array view PINS, or None for an
    owning array.  np.frombuffer views keep the whole downloaded blob
    alive, so the LRU must charge the blob — charging only the view's
    nbytes would let resident RAM exceed the configured budget by the
    blob-to-wanted-columns ratio."""
    base = getattr(arr, "base", None)
    while isinstance(base, type(arr)) and base.base is not None:
        base = base.base  # view-of-view: walk to the owning object
    if base is None:
        return None
    try:
        return id(base), memoryview(base).nbytes
    except TypeError:
        return id(base), int(getattr(base, "nbytes", arr.nbytes))


def _part_nbytes(cols: dict) -> int:
    """Host bytes one {name: (arr, enc)} part keeps RESIDENT: each
    distinct pinned base buffer counted once at its full size, owning
    arrays at their own size, plus dictionary payloads (object
    dictionaries count their string/bytes content, not just the
    pointer array)."""
    total = 0
    bases: dict[int, int] = {}
    for arr, enc in cols.values():
        pinned = _base_size(arr)
        if pinned is not None:
            bases[pinned[0]] = pinned[1]
        else:
            total += int(arr.nbytes)
        d = getattr(enc, "dictionary", None)
        if d is not None:
            if d.dtype == object:
                total += int(d.nbytes) + sum(len(v) for v in d)
            else:
                pinned = _base_size(d)
                if pinned is not None:
                    bases[pinned[0]] = pinned[1]
                else:
                    total += int(d.nbytes)
    return total + sum(bases.values())


class EncodedSegmentCache:
    """Byte-LRU of per-SST encoded parts + the per-SST negative memo.

    An entry maps one immutable SST id to {column name: (unpadded np
    array, ColumnEncoding)} plus the SST's row count.  `get` hits only
    when every wanted column is resident; inserts for an id MERGE
    column sets, so a projection-narrow read widens the entry instead
    of replacing it."""

    def __init__(self, max_bytes: int, write_through: bool = True):
        self.max_bytes = max_bytes
        self.write_through = write_through
        # sst_id -> (cols dict, n_rows, charged bytes)
        self._entries: "OrderedDict[int, tuple[dict, int, int]]" = \
            OrderedDict()
        self._total_bytes = 0
        self._missing: set[int] = set()
        self._failed_assemblies: set[frozenset] = set()
        self.hits = 0
        self.misses = 0
        self.admissions = 0
        self.evictions = 0
        self.invalidated = 0

    @property
    def enabled(self) -> bool:
        return self.max_bytes > 0

    @property
    def total_bytes(self) -> int:
        return self._total_bytes

    def __len__(self) -> int:
        return len(self._entries)

    # ---- read path --------------------------------------------------------

    def get(self, sst_id: int, want) -> Optional[tuple[dict, int]]:
        """({name: (arr, enc)} restricted to `want`, n_rows) when every
        wanted column is resident, else None.  Counts a miss even when
        disabled so operators see the tier working (or not) on
        /metrics."""
        entry = self._entries.get(sst_id)
        if entry is None or not set(want) <= entry[0].keys():
            self.misses += 1
            _MISSES.inc()
            trace_add("cache_tier2_misses")
            return None
        self._entries.move_to_end(sst_id)
        self.hits += 1
        _HITS.inc()
        cols, n, nbytes = entry
        trace_add("cache_tier2_hits")
        trace_add("cache_tier2_bytes", nbytes)
        return {nm: cols[nm] for nm in want}, n

    def peek(self, sst_id: int, want) -> bool:
        """Stats-free residency probe: True iff get() would hit.  No
        LRU bump, no hit/miss counters, no trace attribution — the
        scan pipeline's is-it-worth-it probe runs this over every
        to-read segment and must not distort cache telemetry (the real
        read that follows does the counting)."""
        entry = self._entries.get(sst_id)
        return entry is not None and set(want) <= entry[0].keys()

    def put(self, sst_id: int, cols: dict, n_rows: int) -> None:
        """Read-path insert of a COMPLETE part (all rows of the SST for
        these columns).  ZERO-COPY: the arrays are deserialize's views
        into the downloaded blob, which they keep alive.  The charged
        bytes are the wanted columns' + dictionaries' — a slight
        undercount (the blob's header and block-stats sections ride
        along unpinned-by-name), bounded small because sidecars only
        store the columns scans read and `want` includes essentially
        all of them.  Copying here measurably slowed true-cold scans
        (one extra full-segment memcpy per cold query)."""
        if not self.enabled:
            return
        self._insert(sst_id, dict(cols), n_rows)

    # ---- write path -------------------------------------------------------

    def admit(self, sst_id: int, cols: dict, n_rows: int) -> bool:
        """Write-through insert from the flush/compaction sidecar build
        — the ONE admission door for writers (tools/lint.py rejects
        direct put/get outside the reader).  The arrays are freshly
        encoded (not blob views), so no copy is taken.  Returns whether
        the entry was admitted."""
        if not self.enabled or not self.write_through:
            return False
        self._insert(sst_id, dict(cols), n_rows)
        if sst_id in self._entries:
            self.admissions += 1
            _ADMISSIONS.inc()
            return True
        return False

    def _insert(self, sst_id: int, cols: dict, n_rows: int) -> None:
        old = self._entries.pop(sst_id, None)
        if old is not None:
            self._account(-old[2])
            merged = dict(old[0])
            merged.update(cols)  # widen: keep columns the new part lacks
            cols = merged
        nbytes = _part_nbytes(cols)
        if nbytes > self.max_bytes:
            return
        self._entries[sst_id] = (cols, n_rows, nbytes)
        self._account(nbytes)
        self._missing.discard(sst_id)
        while self._total_bytes > self.max_bytes and self._entries:
            _, (_, _, evicted) = self._entries.popitem(last=False)
            self._account(-evicted)
            self.evictions += 1
            _EVICTIONS.inc()

    # ---- lifecycle --------------------------------------------------------

    def invalidate(self, sst_ids) -> int:
        """Drop entries whose SSTs a compaction/GC just deleted.  Purely
        memory hygiene — ids are immutable so stale entries are
        impossible — but deleted SSTs will never be read again and must
        not squat in the budget.  Their negative memos drop too (the
        ids are gone for good; keeping tombstones wastes the bound)."""
        n = 0
        for sid in sst_ids:
            entry = self._entries.pop(sid, None)
            if entry is not None:
                self._account(-entry[2])
                n += 1
            self._missing.discard(sid)
        if n:
            self.invalidated += n
            _INVALIDATED.inc(n)
        return n

    def clear(self) -> None:
        """Benchmark/test hook (true-cold legs); production invalidation
        is per-SST via invalidate().  Composition-failure memos drop
        too (derived state); per-SST `missing` memos survive — they
        record broken OBJECTS, not cache state."""
        self._account(-self._total_bytes)
        self._entries.clear()
        self._failed_assemblies.clear()

    def _account(self, delta: int) -> None:
        self._total_bytes += delta
        _BYTES.inc(delta)  # delta-based: the gauge aggregates instances

    # ---- negative path ----------------------------------------------------

    def mark_missing(self, sst_id: int) -> None:
        """Memoize one SST id as permanently sidecar-less.  STRICTLY per
        id: callers must only mark ids whose OWN sidecar failed (absent
        or unparseable) — never siblings of a cross-SST failure."""
        if len(self._missing) > _MISSING_MAX:
            self._missing.clear()
        self._missing.add(sst_id)

    def is_missing(self, sst_id: int) -> bool:
        return sst_id in self._missing

    def mark_assembly_failed(self, sst_ids) -> None:
        """Memoize one COMPOSITION (frozenset of SST ids) whose
        cross-SST assembly failed — e.g. a union dictionary at the pad
        sentinel.  Objects are immutable so the failure is permanent
        for this exact set, and later cold scans skip its sidecar GETs
        — but the member ids stay individually valid: any OTHER
        composition (post-compaction, other segments) tries afresh.
        This replaces the old whole-set `missing` memo, which poisoned
        every member forever."""
        if len(self._failed_assemblies) > _MISSING_MAX:
            self._failed_assemblies.clear()
        self._failed_assemblies.add(frozenset(sst_ids))

    def is_assembly_failed(self, sst_ids) -> bool:
        return frozenset(sst_ids) in self._failed_assemblies

    # ---- observability ----------------------------------------------------

    def stats(self) -> dict:
        return {
            "entries": len(self._entries),
            "bytes": self._total_bytes,
            "max_bytes": self.max_bytes,
            "write_through": self.write_through,
            "hits": self.hits,
            "misses": self.misses,
            "admissions": self.admissions,
            "evictions": self.evictions,
            "invalidated": self.invalidated,
            "negative_entries": len(self._missing),
            "failed_assemblies": len(self._failed_assemblies),
        }
