"""HBM-resident scan cache.

The north star keeps the scan path operating "over HBM-resident
RecordBatches" — steady-state queries should not re-decode Parquet,
re-encode columns, or re-run the merge sort.  This cache stores each
segment's POST-MERGE device windows keyed by

    (segment_start, frozenset of SST ids, column tuple)

so correctness falls out structurally: any write or compaction changes
the segment's SST set and therefore misses the cache (no explicit
invalidation hooks, no staleness).  Predicates and aggregation run AFTER
the merge, so one cached entry serves every query shape over the same
data.

Eviction is LRU by total cached BYTES — column buffers across their
real widths plus an allowance for the per-window aggregation memos
(each memo slot can hold a capacity-sized gid array); dropping an entry
releases its device buffers through JAX's reference counting.
"""

from __future__ import annotations

from collections import OrderedDict

from horaedb_tpu.utils import registry, trace_add

# shared labeled families across the cache tiers (tier="hbm" here,
# tier="tier2" in storage/encoded_cache.py) — one series per tier
# instead of per-tier metric names
_HITS = registry.counter("scan_cache_hits_total",
                         "scan cache hits by tier").labels(tier="hbm")
_MISSES = registry.counter("scan_cache_misses_total",
                           "scan cache misses by tier").labels(tier="hbm")
_EVICTIONS = registry.counter("scan_cache_evictions_total",
                              "scan cache evictions by tier"
                              ).labels(tier="hbm")

CacheKey = tuple

# DeviceBatch.memo allowance multiplier: the reader's byte-bounded memo
# store (storage.read._memo_store) caps each window's memo values at
# MEMO_SLOTS * (capacity*4 + 128) REAL bytes — entries vary in size (a
# window_groups gid is 4 B/row, a dev_cols entry 12 B/row, i.e. three
# "slots" worth), so at the current value the worst-case resident pair
# (gid + dev_cols = 16 B/row) fits exactly.  Lowering MEMO_SLOTS below
# 3 would make a single dev_cols entry exceed the budget and thrash.
MEMO_SLOTS = 4


def segment_cache_key(segment_start: int, sst_ids, columns) -> CacheKey:
    return (segment_start, frozenset(sst_ids), tuple(columns))


def windows_nbytes(windows: list) -> int:
    """HBM cost of a cached entry: every column buffer at its real
    width, plus the memo allowance per window."""
    total = 0
    for w in windows:
        for col in w.columns.values():
            total += int(col.dtype.itemsize) * w.capacity
        total += MEMO_SLOTS * (w.capacity * 4 + 128)
    return total


class ByteLRU:
    """Byte-budgeted LRU core (event-loop owned — no lock).  Counters
    are the caller's registry counters, so every cache built on this
    core is operator-visible on /metrics."""

    def __init__(self, max_bytes: int, hits=None, misses=None,
                 evictions=None, trace_tier: str = ""):
        self.max_bytes = max_bytes
        self._entries: "OrderedDict[CacheKey, tuple[object, int]]" = \
            OrderedDict()
        self._total_bytes = 0
        self._hits = hits
        self._misses = misses
        self._evictions = evictions
        self.hits = 0
        self.misses = 0
        # per-query attribution name ("cache_<tier>_*" trace counters
        # on the ambient trace); "" = no trace attribution — each LRU
        # built on this core must name its own tier, exactly like it
        # passes its own registry counters
        self.trace_tier = trace_tier

    def get(self, key: CacheKey):
        entry = self._entries.get(key)
        if entry is None:
            self.record_miss()
            return None
        self._entries.move_to_end(key)
        self._count_hit(entry)
        return entry[0]

    def peek_entry(self, key: CacheKey):
        """Stats-free, recency-free lookup.  For callers that must
        VALIDATE an entry before it counts as served (PartsMemo
        coverage): they account the outcome themselves via
        record_hit/record_miss, so a found-but-unusable entry is not
        reported as a hit."""
        entry = self._entries.get(key)
        return None if entry is None else entry[0]

    def record_miss(self) -> None:
        self.misses += 1
        if self._misses is not None:
            self._misses.inc()
        if self.trace_tier:
            trace_add(f"cache_{self.trace_tier}_misses")

    def record_hit(self, key: CacheKey) -> None:
        entry = self._entries.get(key)
        if entry is None:
            return
        self._entries.move_to_end(key)
        self._count_hit(entry)

    def _count_hit(self, entry) -> None:
        self.hits += 1
        if self._hits is not None:
            self._hits.inc()
        if self.trace_tier:
            trace_add(f"cache_{self.trace_tier}_hits")
            trace_add(f"cache_{self.trace_tier}_bytes", entry[1])

    def put(self, key: CacheKey, value, nbytes: int) -> None:
        if self.max_bytes <= 0 or nbytes > self.max_bytes:
            return
        if key in self._entries:
            self._total_bytes -= self._entries.pop(key)[1]
        self._entries[key] = (value, nbytes)
        self._total_bytes += nbytes
        while self._total_bytes > self.max_bytes and self._entries:
            _, (_, evicted) = self._entries.popitem(last=False)
            self._total_bytes -= evicted
            if self._evictions is not None:
                self._evictions.inc()

    def clear(self) -> None:
        self._entries.clear()
        self._total_bytes = 0

    def __len__(self) -> int:
        return len(self._entries)

    @property
    def total_bytes(self) -> int:
        return self._total_bytes

    def values(self):
        """Resident values in LRU order (no recency update) — the
        reader's HBM-eviction sweep walks cached windows through this."""
        return [v for v, _nbytes in self._entries.values()]

    @property
    def total_bytes(self) -> int:
        return self._total_bytes

    def __len__(self) -> int:
        return len(self._entries)


class ScanCache(ByteLRU):
    """Post-merge window cache (see module docstring): the ByteLRU core
    with window-aware byte accounting and the scan_cache_* counters."""

    def __init__(self, max_bytes: int):
        super().__init__(max_bytes, hits=_HITS, misses=_MISSES,
                         evictions=_EVICTIONS, trace_tier="hbm")

    def put(self, key: CacheKey, windows: list) -> None:  # type: ignore[override]
        super().put(key, windows, windows_nbytes(windows))

    def clear(self) -> None:
        """Drop every entry (releases device buffers via refcounting).
        Used by cold-path benchmarks and tests; production invalidation
        is structural (SST-set keys), never explicit."""
        super().clear()
