"""TimeMergeStorage: LSM-on-object-storage engine (ref: src/storage)."""

from horaedb_tpu.storage.config import (
    ColumnOptions,
    ManifestConfig,
    SchedulerConfig,
    StorageConfig,
    UpdateMode,
    WriteConfig,
)
from horaedb_tpu.storage.types import (
    BUILTIN_COLUMN_NUM,
    RESERVED_COLUMN_NAME,
    SEQ_COLUMN_NAME,
    StorageSchema,
    Timestamp,
    TimeRange,
)

__all__ = [
    "BUILTIN_COLUMN_NUM",
    "ColumnOptions",
    "ManifestConfig",
    "RESERVED_COLUMN_NAME",
    "SEQ_COLUMN_NAME",
    "SchedulerConfig",
    "StorageConfig",
    "StorageSchema",
    "TimeRange",
    "Timestamp",
    "UpdateMode",
    "WriteConfig",
]
