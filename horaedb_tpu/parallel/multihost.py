"""Multi-host (multi-process) mesh plumbing — the DCN tier.

The reference's sharding RFC scales out with HoraeMeta + gRPC
forwarding (docs/rfcs/20240827-metric-engine.md:20-76); the engine's
own data plane has no cross-node compute.  The TPU-native design
instead runs ONE SPMD program over a global device mesh spanning
processes/hosts: each process contributes its local segment windows,
`jax.lax` collectives (psum/pmin/pmax — the same ops that ride ICI
within a pod) combine partial grids ACROSS hosts over DCN, and every
process receives the replicated result.  On real TPU pods
`jax.distributed.initialize()` auto-detects topology; the CPU Gloo
backend runs the identical program across local processes, which is
how the tests exercise true cross-process collectives without TPU
hardware (see tests/test_multihost.py).

The segment axis stays the ONE mesh axis (parallel/mesh.py): segments
partition time, so cross-host combination is the same psum tree the
single-host mesh path uses — no new program shapes, just more devices
under the same axis name.
"""

from __future__ import annotations

import numpy as np

from horaedb_tpu.common.error import ensure
# shared with the single-host mesh programs — importing mesh.py does NOT
# initialize the XLA backend (module imports only)
from horaedb_tpu.parallel.mesh import SEGMENT_AXIS


def initialize(coordinator_address: str | None = None,
               num_processes: int | None = None,
               process_id: int | None = None,
               local_device_count: int | None = None) -> None:
    """Join (or form) a multi-process JAX runtime.

    On TPU pods call with no arguments — topology is auto-detected.
    For CPU-backed tests/dev, pass the coordinator plus this process's
    rank, and optionally force `local_device_count` virtual CPU devices
    (must happen before first backend use; see utils/cpu_mesh.py for
    why the env var alone is not enough under the axon plugin)."""
    if local_device_count is not None:
        from horaedb_tpu.utils.cpu_mesh import force_cpu_devices

        force_cpu_devices(local_device_count)
    import jax

    kwargs = {}
    if coordinator_address is not None:
        kwargs = dict(coordinator_address=coordinator_address,
                      num_processes=num_processes,
                      process_id=process_id)
    jax.distributed.initialize(**kwargs)


def global_segment_mesh():
    """A 1-D mesh over EVERY device of EVERY process, on the same
    segment axis the single-host mesh uses — collectives cross hosts
    transparently."""
    import jax
    from jax.sharding import Mesh

    devices = np.asarray(jax.devices())
    ensure(devices.size > 0, "no devices for the global mesh")
    return Mesh(devices, (SEGMENT_AXIS,))


def host_local_rows_to_global(mesh, arr: np.ndarray):
    """Lift this process's (n_local, ...) segment rows into the global
    (n_global, ...) sharded array the SPMD query consumes.  Every
    process must contribute the same n_local (pad with empty windows —
    n_valid 0 rows aggregate to nothing)."""
    from jax.experimental import multihost_utils
    from jax.sharding import PartitionSpec as P

    spec = P(SEGMENT_AXIS, *([None] * (np.ndim(arr) - 1)))
    return multihost_utils.host_local_array_to_global_array(
        np.asarray(arr), mesh, spec)


def downsample_query_global(mesh, *, num_groups: int, num_buckets: int,
                            k: int):
    """The multi-chip downsample+topk program (parallel.scan) compiled
    over a GLOBAL mesh: per-shard partial grids, cross-host
    psum/pmin/pmax combine, replicated finalized output on every
    process.  Inputs must be global arrays (host_local_rows_to_global);
    the replicated outputs are addressable on every process via
    `np.asarray(out.addressable_data(0))`."""
    from horaedb_tpu.parallel.scan import sharded_downsample_query

    return sharded_downsample_query(mesh, num_groups=num_groups,
                                    num_buckets=num_buckets, k=k)


def process_info() -> tuple[int, int]:
    """(process_index, process_count) of the joined runtime."""
    import jax

    return jax.process_index(), jax.process_count()
