"""shard_map scan programs over the segment mesh.

Data layout: host stacks per-segment device batches into
(n_devices, capacity) arrays, sharded on the leading (segment) axis.
Segments never share primary keys with each other in OVERWRITE semantics
terms (a PK's rows live in one segment at a time... strictly: dedup is
segment-scoped by design, matching the reference where each segment gets
its own MergeExec), so:

- merge-dedup is purely shard-local (no collective at all);
- downsampling combines per-shard partial grids with psum (sum/count),
  pmin/pmax (min/max), and an argmax-by-timestamp scheme for `last`
  (later shard wins ties, mirroring later-file-wins);
- top-k runs on the replicated combined grid.

Collectives ride ICI inside one compiled program — the XLA analogue of
the reference's cross-partition SortPreservingMergeExec, except only
(groups x buckets) floats cross chips instead of row streams.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

try:
    from jax import shard_map
except ImportError:  # older jax: pre-promotion experimental namespace
    from jax.experimental.shard_map import shard_map as _shard_map_compat

    def shard_map(f, *, check_vma=True, **kw):
        # the experimental API spells replication checking `check_rep`
        return _shard_map_compat(f, check_rep=check_vma, **kw)
from jax.sharding import NamedSharding, PartitionSpec as P

from horaedb_tpu.common.error import Error
from horaedb_tpu.ops import downsample, merge as merge_ops
from horaedb_tpu.ops.topk import top_k_groups
from horaedb_tpu.parallel.mesh import SEGMENT_AXIS


def _check_block_is_one(block) -> None:
    """The shard programs index block [0]; a leading axis larger than the
    mesh would silently drop segments.  Fail at trace time instead."""
    if block.shape[0] != 1:
        raise Error(
            f"leading axis {block.shape[0]} exceeds the mesh: stack exactly "
            "one segment batch per device (pad the device axis, or scan in "
            "rounds)")


def _combine_partials(p: dict) -> dict:
    """Cross-shard combination of partial aggregate grids."""
    ax = SEGMENT_AXIS
    combined = {
        "count": jax.lax.psum(p["count"], ax),
        "sum": jax.lax.psum(p["sum"], ax),
        "min": jax.lax.pmin(p["min"], ax),
        "max": jax.lax.pmax(p["max"], ax),
    }
    # `last`: the shard holding the globally-latest timestamp wins; ties
    # break toward the higher shard index (later segment).
    g_last_ts = jax.lax.pmax(p["last_ts"], ax)
    rank = jax.lax.axis_index(ax)
    eligible = p["last_ts"] == g_last_ts
    g_rank = jax.lax.pmax(jnp.where(eligible, rank, -1), ax)
    winner = eligible & (rank == g_rank)
    combined["last"] = jax.lax.psum(jnp.where(winner, p["last"], 0.0), ax)
    combined["last_ts"] = g_last_ts
    return combined


def sharded_downsample_query(mesh, *, num_groups: int, num_buckets: int,
                             k: int):
    """Build the compiled multi-chip downsample+topk query.

    Returns fn(ts_offset, group_ids, values, n_valid, bucket_ms) where the
    first three args are (n_devices, capacity) int32/int32/float32 arrays
    sharded on the leading axis, n_valid is (n_devices,) int32, and
    bucket_ms is a replicated scalar.  Output: replicated dict of
    (num_groups, num_buckets) finalized grids + (top_k values, indices).
    """

    def shard_fn(ts, gid, vals, n_valid, bucket_ms):
        p = _shard_partial(ts, gid, vals, n_valid, bucket_ms,
                           num_groups=num_groups, num_buckets=num_buckets)
        combined = _combine_partials(p)
        final = downsample.finalize_aggregate(combined)
        scores = jnp.max(jnp.where(final["count"] > 0, final["max"],
                                   -jnp.inf), axis=1).astype(jnp.float32)
        top_vals, top_idx = top_k_groups(scores, k=k)
        return final, top_vals, top_idx

    mapped = shard_map(
        shard_fn, mesh=mesh,
        in_specs=_ROW_SPECS,
        out_specs=(P(), P(), P()),
        check_vma=False,
    )
    return jax.jit(mapped)


def _shard_partial(ts, gid, vals, n_valid, bucket_ms, *, num_groups: int,
                   num_buckets: int) -> dict:
    """Per-shard prelude shared by the mesh aggregation programs: one
    window's partial grids from its (1, capacity) block."""
    _check_block_is_one(ts)
    return downsample.partial_aggregate(
        ts[0], gid[0], vals[0], n_valid[0], bucket_ms[0],
        num_groups=num_groups, num_buckets=num_buckets)


_ROW_SPECS = (P(SEGMENT_AXIS, None), P(SEGMENT_AXIS, None),
              P(SEGMENT_AXIS, None), P(SEGMENT_AXIS), P())


def sharded_remap_partials(mesh, *, num_groups: int, num_buckets: int,
                           which: tuple = downsample.ALL_AGGS):
    """Batched multi-chip partial aggregation with the per-window group
    remap fused into the compiled program.

    Windows from DIFFERENT segments batch onto the mesh (the reference's
    UnionExec axis, storage.rs:342-368): each chip remaps its window's
    local dense group ids into the round's union group space via a
    (num_groups,) remap row, shifts timestamps into query-range offsets,
    and aggregates into a window-LOCAL grid (num_buckets wide, starting
    at the window's `lo` bucket) — all without leaving the device.
    Per-shard grids come back stacked (n_devices, G, B) for the host's
    float64 fold (bit-equal to the single-device path).

    fn(ts, gid, vals, remap, shift, lo, total_buckets, bucket_ms):
      ts/gid/vals: (n_devices, capacity) sharded on the leading axis,
        gid rows are window-local dense codes with -1 = dropped row;
      remap: (n_devices, num_groups) int32 — local code -> union row;
      shift: (n_devices,) int32 added to ts (per-window epoch offset);
      lo: (n_devices,) int32 first covered bucket per window;
      total_buckets: replicated scalar — global bucket count;
      bucket_ms: (1,) replicated.
    """

    def shard_fn(ts, gid, vals, remap, shift, lo, total, bucket_ms):
        _check_block_is_one(ts)
        p = downsample.window_local_partials(
            ts[0], gid[0], vals[0], remap[0], shift[0], lo[0], total,
            bucket_ms[0], num_groups=num_groups, num_buckets=num_buckets,
            which=which)
        return {k: v[None] for k, v in p.items()}

    mapped = shard_map(
        shard_fn, mesh=mesh,
        in_specs=(P(SEGMENT_AXIS, None), P(SEGMENT_AXIS, None),
                  P(SEGMENT_AXIS, None), P(SEGMENT_AXIS, None),
                  P(SEGMENT_AXIS), P(SEGMENT_AXIS), P(), P()),
        out_specs=P(SEGMENT_AXIS),
        check_vma=False,
    )
    return jax.jit(mapped)


def _build_sharded_merge(mesh, merge_fn):
    """Shared shard_map plumbing for the two merge kernels: unwrap the
    (1, capacity) blocks, run `merge_fn` shard-locally (dedup is
    segment-scoped, so NO collectives), re-expand the leading axis."""

    def shard_fn(pks, seq, values, n_valid):
        _check_block_is_one(seq)
        out_pks, out_seq, out_vals, out_valid, num_runs = merge_fn(
            tuple(c[0] for c in pks), seq[0],
            tuple(v[0] for v in values), n_valid[0])
        expand = lambda a: a[None, :]
        return (tuple(expand(c) for c in out_pks), expand(out_seq),
                tuple(expand(v) for v in out_vals), expand(out_valid),
                num_runs[None])

    mapped = shard_map(
        shard_fn, mesh=mesh,
        in_specs=(P(SEGMENT_AXIS, None), P(SEGMENT_AXIS, None),
                  P(SEGMENT_AXIS, None), P(SEGMENT_AXIS)),
        out_specs=(P(SEGMENT_AXIS, None), P(SEGMENT_AXIS, None),
                   P(SEGMENT_AXIS, None), P(SEGMENT_AXIS, None),
                   P(SEGMENT_AXIS)),
        check_vma=False,
    )
    return jax.jit(mapped)


def sharded_merge_dedup(mesh, *, num_pks: int):
    """Build the compiled multi-chip merge-dedup.

    Segments are the shard axis and dedup is segment-scoped, so this is
    shard-local compute with NO collectives — the mesh exists so the same
    program scales from 1 to N chips and composes with the downsample
    collectives in one jit.

    Returns fn(pks, seq, values, n_valid) over (n_devices, capacity)
    arrays; outputs keep the same sharded layout plus a per-shard
    (n_devices,) run count.
    """
    del num_pks  # shape-polymorphic: the tuple arity fixes it at trace
    return _build_sharded_merge(mesh, merge_ops.merge_dedup_last)


def shard_leading_axis(mesh, arr):
    """Place an (n_devices, ...) host array sharded over the segment axis."""
    return jax.device_put(arr, NamedSharding(mesh, P(SEGMENT_AXIS)))
