"""shard_map scan programs over the segment mesh.

Data layout: host stacks per-segment device batches into
(n_devices, capacity) arrays, sharded on the leading (segment) axis.
Segments never share primary keys with each other in OVERWRITE semantics
terms (a PK's rows live in one segment at a time... strictly: dedup is
segment-scoped by design, matching the reference where each segment gets
its own MergeExec), so:

- merge-dedup is purely shard-local (no collective at all);
- downsampling combines per-shard partial grids with psum (sum/count),
  pmin/pmax (min/max), and an argmax-by-timestamp scheme for `last`
  (later shard wins ties, mirroring later-file-wins);
- top-k runs on the replicated combined grid.

Collectives ride ICI inside one compiled program — the XLA analogue of
the reference's cross-partition SortPreservingMergeExec, except only
(groups x buckets) floats cross chips instead of row streams.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

try:
    from jax import shard_map
except ImportError:  # older jax: pre-promotion experimental namespace
    import inspect

    from jax.experimental.shard_map import shard_map as _shard_map_compat

    _COMPAT_PARAMS = inspect.signature(_shard_map_compat).parameters
    _COMPAT_VAR_KW = any(
        p.kind is inspect.Parameter.VAR_KEYWORD
        for p in _COMPAT_PARAMS.values())

    def shard_map(f, *, check_vma=True, **kw):
        """Compat shim for pre-promotion jax: the experimental API
        spells replication checking `check_rep`.  Every other kwarg
        forwards verbatim, and one this jax's shard_map does not accept
        raises HERE with the offending names — silently dropping it
        would mask future jax API drift behind subtly-wrong programs."""
        if "check_rep" in _COMPAT_PARAMS or _COMPAT_VAR_KW:
            kw.setdefault("check_rep", check_vma)
        elif "check_vma" in _COMPAT_PARAMS:
            kw.setdefault("check_vma", check_vma)
        else:
            raise TypeError(
                "jax.experimental.shard_map.shard_map accepts neither "
                "check_rep nor check_vma; update the compat shim in "
                "horaedb_tpu/parallel/scan.py for this jax version")
        if not _COMPAT_VAR_KW:
            unknown = sorted(k for k in kw if k not in _COMPAT_PARAMS)
            if unknown:
                raise TypeError(
                    f"shard_map compat shim: kwargs {unknown} are not "
                    "accepted by this jax version's experimental "
                    "shard_map — fix the call site or the shim, do not "
                    "drop them")
        return _shard_map_compat(f, **kw)
from jax.sharding import NamedSharding, PartitionSpec as P

from horaedb_tpu.common import deviceprof
from horaedb_tpu.common.error import Error
from horaedb_tpu.ops import downsample, merge as merge_ops
from horaedb_tpu.ops.topk import (pair_add, pair_max_normalized,
                                  top_k_groups)
from horaedb_tpu.parallel.mesh import SEGMENT_AXIS, SERIES_AXIS, TIME_AXIS


def _check_block_is_one(block) -> None:
    """The shard programs index block [0]; a leading axis larger than the
    mesh would silently drop segments.  Fail at trace time instead."""
    if block.shape[0] != 1:
        raise Error(
            f"leading axis {block.shape[0]} exceeds the mesh: stack exactly "
            "one segment batch per device (pad the device axis, or scan in "
            "rounds)")


def _combine_partials(p: dict) -> dict:
    """Cross-shard combination of partial aggregate grids."""
    ax = SEGMENT_AXIS
    combined = {
        "count": jax.lax.psum(p["count"], ax),
        "sum": jax.lax.psum(p["sum"], ax),
        "min": jax.lax.pmin(p["min"], ax),
        "max": jax.lax.pmax(p["max"], ax),
    }
    # `last`: the shard holding the globally-latest timestamp wins; ties
    # break toward the higher shard index (later segment).
    g_last_ts = jax.lax.pmax(p["last_ts"], ax)
    rank = jax.lax.axis_index(ax)
    eligible = p["last_ts"] == g_last_ts
    g_rank = jax.lax.pmax(jnp.where(eligible, rank, -1), ax)
    winner = eligible & (rank == g_rank)
    combined["last"] = jax.lax.psum(jnp.where(winner, p["last"], 0.0), ax)
    combined["last_ts"] = g_last_ts
    return combined


def sharded_downsample_query(mesh, *, num_groups: int, num_buckets: int,
                             k: int):
    """Build the compiled multi-chip downsample+topk query.

    Returns fn(ts_offset, group_ids, values, n_valid, bucket_ms) where the
    first three args are (n_devices, capacity) int32/int32/float32 arrays
    sharded on the leading axis, n_valid is (n_devices,) int32, and
    bucket_ms is a replicated scalar.  Output: replicated dict of
    (num_groups, num_buckets) finalized grids + (top_k values, indices).
    """

    def shard_fn(ts, gid, vals, n_valid, bucket_ms):
        p = _shard_partial(ts, gid, vals, n_valid, bucket_ms,
                           num_groups=num_groups, num_buckets=num_buckets)
        combined = _combine_partials(p)
        final = downsample.finalize_aggregate(combined)
        scores = jnp.max(jnp.where(final["count"] > 0, final["max"],
                                   -jnp.inf), axis=1).astype(jnp.float32)
        top_vals, top_idx = top_k_groups(scores, k=k)
        return final, top_vals, top_idx

    mapped = shard_map(
        shard_fn, mesh=mesh,
        in_specs=_ROW_SPECS,
        out_specs=(P(), P(), P()),
        check_vma=False,
    )
    return deviceprof.jit(mapped, name="sharded_downsample_query")


def _shard_partial(ts, gid, vals, n_valid, bucket_ms, *, num_groups: int,
                   num_buckets: int) -> dict:
    """Per-shard prelude shared by the mesh aggregation programs: one
    window's partial grids from its (1, capacity) block."""
    _check_block_is_one(ts)
    return downsample.partial_aggregate(
        ts[0], gid[0], vals[0], n_valid[0], bucket_ms[0],
        num_groups=num_groups, num_buckets=num_buckets)


_ROW_SPECS = (P(SEGMENT_AXIS, None), P(SEGMENT_AXIS, None),
              P(SEGMENT_AXIS, None), P(SEGMENT_AXIS), P())


def sharded_remap_partials(mesh, *, num_groups: int, num_buckets: int,
                           which: tuple = downsample.ALL_AGGS):
    """Batched multi-chip partial aggregation with the per-window group
    remap fused into the compiled program.

    Windows from DIFFERENT segments batch onto the mesh (the reference's
    UnionExec axis, storage.rs:342-368): each chip remaps its window's
    local dense group ids into the round's union group space via a
    (num_groups,) remap row, shifts timestamps into query-range offsets,
    and aggregates into a window-LOCAL grid (num_buckets wide, starting
    at the window's `lo` bucket) — all without leaving the device.
    Per-shard grids come back stacked (n_devices, G, B) for the host's
    float64 fold (bit-equal to the single-device path).

    fn(ts, gid, vals, remap, shift, lo, total_buckets, bucket_ms):
      ts/gid/vals: (n_devices, capacity) sharded on the leading axis,
        gid rows are window-local dense codes with -1 = dropped row;
      remap: (n_devices, num_groups) int32 — local code -> union row;
      shift: (n_devices,) int32 added to ts (per-window epoch offset);
      lo: (n_devices,) int32 first covered bucket per window;
      total_buckets: replicated scalar — global bucket count;
      bucket_ms: (1,) replicated.
    """

    def shard_fn(ts, gid, vals, remap, shift, lo, total, bucket_ms):
        _check_block_is_one(ts)
        p = downsample.window_local_partials(
            ts[0], gid[0], vals[0], remap[0], shift[0], lo[0], total,
            bucket_ms[0], num_groups=num_groups, num_buckets=num_buckets,
            which=which)
        return {k: v[None] for k, v in p.items()}

    mapped = shard_map(
        shard_fn, mesh=mesh,
        in_specs=(P(SEGMENT_AXIS, None), P(SEGMENT_AXIS, None),
                  P(SEGMENT_AXIS, None), P(SEGMENT_AXIS, None),
                  P(SEGMENT_AXIS), P(SEGMENT_AXIS), P(), P()),
        out_specs=P(SEGMENT_AXIS),
        check_vma=False,
    )
    return deviceprof.jit(mapped, name="sharded_remap_partials")


def _build_sharded_merge(mesh, merge_fn):
    """Shared shard_map plumbing for the two merge kernels: unwrap the
    (1, capacity) blocks, run `merge_fn` shard-locally (dedup is
    segment-scoped, so NO collectives), re-expand the leading axis."""

    def shard_fn(pks, seq, values, n_valid):
        _check_block_is_one(seq)
        out_pks, out_seq, out_vals, out_valid, num_runs = merge_fn(
            tuple(c[0] for c in pks), seq[0],
            tuple(v[0] for v in values), n_valid[0])
        expand = lambda a: a[None, :]
        return (tuple(expand(c) for c in out_pks), expand(out_seq),
                tuple(expand(v) for v in out_vals), expand(out_valid),
                num_runs[None])

    mapped = shard_map(
        shard_fn, mesh=mesh,
        in_specs=(P(SEGMENT_AXIS, None), P(SEGMENT_AXIS, None),
                  P(SEGMENT_AXIS, None), P(SEGMENT_AXIS)),
        out_specs=(P(SEGMENT_AXIS, None), P(SEGMENT_AXIS, None),
                   P(SEGMENT_AXIS, None), P(SEGMENT_AXIS, None),
                   P(SEGMENT_AXIS)),
        check_vma=False,
    )
    return deviceprof.jit(
        mapped, name=f"sharded_merge[{merge_fn.__name__}]")


def sharded_merge_dedup(mesh, *, num_pks: int):
    """Build the compiled multi-chip merge-dedup.

    Segments are the shard axis and dedup is segment-scoped, so this is
    shard-local compute with NO collectives — the mesh exists so the same
    program scales from 1 to N chips and composes with the downsample
    collectives in one jit.

    Returns fn(pks, seq, values, n_valid) over (n_devices, capacity)
    arrays; outputs keep the same sharded layout plus a per-shard
    (n_devices,) run count.
    """
    del num_pks  # shape-polymorphic: the tuple arity fixes it at trace
    return _build_sharded_merge(mesh, merge_ops.merge_dedup_last)


def shard_leading_axis(mesh, arr):
    """Place an (n_devices, ...) host array sharded over the segment axis."""
    return deviceprof.device_put(arr, NamedSharding(mesh, P(SEGMENT_AXIS)))


# ---------------------------------------------------------------------------
# the 2-D (time, series) scan mesh ([scan.mesh]; docs/parallel.md)
# ---------------------------------------------------------------------------


def shard_time_axis(mesh, arr):
    """Place a (time, ...) host array sharded over the scan mesh's time
    axis, replicated over series.  Series shards re-aggregate every row
    for their own group block — the series axis divides resident grid
    STATE and combine egress, not row work (the output-parallel layout;
    docs/parallel.md)."""
    return deviceprof.device_put(arr, NamedSharding(mesh, P(TIME_AXIS)))


def mesh_run_partials(mesh, *, num_groups: int, num_buckets: int,
                      which: tuple):
    """The 2-D mesh scan program: per-window partial grids sharded
    (time = one merge window per slot, series = group blocks) and a
    SEGMENTED reduction over the time axis — same-segment slots combine
    into per-run grids via a log2(time) ppermute tree, different
    segments never mix (parts stay per-segment, the PartsMemo / replan
    contract).

    fn(ts, gid, vals, remap, shift, lo, seg_ids, total, bucket_ms):
      ts/gid/vals: (time, capacity) sharded on the time axis;
      remap: (time, num_groups) int32 — window-local code -> round row;
      shift/lo: (time,) int32 per-window epoch offset / first bucket;
      seg_ids: (time,) int32 — slots of one segment share an id and
        are CONSECUTIVE (plan-order slot admission); padding slots
        carry unique negative ids so they never combine;
      total: replicated scalar global bucket count; bucket_ms: (1,).

    Output: dict of (time, num_groups, num_buckets) grids sharded
    (time, series); slot t holds the combined grids of its segment's
    slots up to t (inclusive segmented scan), so a run's TAIL slot
    holds the whole run — the host downloads tails only.

    Exactness contract (the mesh-off byte-identity proof, chaos
    -asserted): each window's partials are computed by the SAME
    full-width scatter program as the single-device path and only then
    block-sliced per series shard; the time-axis combine is exact for
    count (integer f32 adds, dispatcher-bounded < 2^24), min/max/last
    (selection ops, later-slot tie-break = the host fold's `>=` take),
    and for sum exactly when no cell has two contributing windows —
    the dispatcher's overlap gate routes anything else off the mesh
    (read.py _flush_mesh_round)."""
    time_n = int(mesh.shape[TIME_AXIS])
    series_n = int(mesh.shape[SERIES_AXIS])
    gb = _series_block(num_groups, series_n)

    def shard_fn(ts, gid, vals, remap, shift, lo, seg_ids, total,
                 bucket_ms):
        _check_block_is_one(ts)
        p = downsample.window_local_partials(
            ts[0], gid[0], vals[0], remap[0], shift[0], lo[0], total,
            bucket_ms[0], num_groups=num_groups,
            num_buckets=num_buckets, which=which)
        p = _series_slice(p, gb)
        state = _segmented_time_combine(p, seg_ids, time_n)
        return {k: v[None] for k, v in state.items()}

    mapped = shard_map(
        shard_fn, mesh=mesh,
        in_specs=(P(TIME_AXIS, None), P(TIME_AXIS, None),
                  P(TIME_AXIS, None), P(TIME_AXIS, None),
                  P(TIME_AXIS), P(TIME_AXIS), P(TIME_AXIS), P(), P()),
        out_specs=P(TIME_AXIS, SERIES_AXIS),
        check_vma=False,
    )
    return deviceprof.jit(mapped, name="mesh_run_partials")


def _series_block(num_groups: int, series_n: int) -> int:
    if num_groups % series_n:
        raise Error(
            f"mesh group space {num_groups} not divisible by the "
            f"series axis ({series_n}) — pad g to a multiple")
    return num_groups // series_n


def _series_slice(p: dict, gb: int) -> dict:
    """Full-width compute, series-block slice AFTER: the scatter
    program (and therefore every cell's f32 accumulation order) is the
    single-device kernel's; only the RESIDENT state and the collective
    payload shrink to the (gb, width) block."""
    j = jax.lax.axis_index(SERIES_AXIS)
    return {k: jax.lax.dynamic_slice_in_dim(v, j * gb, gb, axis=0)
            for k, v in p.items()}


def _segmented_time_combine(state: dict, seg_ids, time_n: int) -> dict:
    """Inclusive SEGMENTED scan over the time axis via a log2(time)
    ppermute tree: a slot folds in its left neighbour's prefix ONLY
    when it belongs to the same segment (seg id match; ppermute hands
    zeros to slots with no left neighbour — prev_live masks them out).
    Shared by the host-decoded round program above and the fused
    decode round program below — the combine IS the byte-identity
    surface, so both programs must ride the same one."""
    sid = seg_ids  # (1,) block: ppermute needs an array operand
    step = 1
    while step < time_n:
        perm = [(i, i + step) for i in range(time_n - step)]

        def recv(a, _perm=perm):
            return jax.lax.ppermute(a, TIME_AXIS, _perm)

        prev = {k: recv(v) for k, v in state.items()}
        prev_sid = recv(sid)
        prev_live = recv(jnp.ones_like(sid))
        ok = (prev_live[0] > 0) & (prev_sid[0] == sid[0])
        combined = downsample.combine_partial_pair(state, prev)
        state = {k: jnp.where(ok, combined[k], state[k])
                 for k in state}
        step *= 2
    return state


def mesh_decode_partials(mesh, *, num_groups: int, num_buckets: int,
                         which: tuple, key_slots: tuple, num_pks: int,
                         group_pos: int, ts_pos: int, val_slot: int,
                         leaf_prog: tuple, route: str, num_runs: int):
    """The mesh-placed FUSED decode round: each time slot starts from
    its segment's raw encoded sidecar buffers and runs leaf-filter →
    (k-way merge | sort | presorted) → keep-last dedup → bucket
    aggregate → ppermute segmented combine in ONE shard_map program —
    decode shards along the time axis with the aggregation instead of
    serializing ahead of it on one chip (ROADMAP item 1).

    Static decode geometry (key_slots/leaf_prog/route/...) comes from
    the round's DecodePlan group (ops/device_decode.plan_dispatch);
    the dispatcher only batches plans whose DecodePlan.static_key()
    agree, so one compiled program serves the whole round.

    fn(cols, n_valid, leaf_consts, run_offsets, shift, lo, seg_ids,
       total, bucket_ms):
      cols: tuple of (time, capacity) int32 encoded code columns,
        sharded on the time axis (one segment's buffers per slot);
      n_valid: (time,) int32 real row counts (suffix is padding);
      leaf_consts: tuple of (time, L_i) int32 leaf-constant stacks
        (row t = slot t's constants for leaf i, padded by repetition);
      run_offsets: (time, num_runs + 1) int32 per-slot run bounds
        (all-capacity rows for non-kway routes ride along unused);
      shift/lo/seg_ids: (time,) int32 as in mesh_run_partials;
      total: replicated scalar global bucket count; bucket_ms: (1,).

    Slot-local group codes ARE the round rows (identity remap): the
    dispatcher gives same-segment slots a shared seg id only when
    their dictionaries match, so the combine never mixes code spaces.
    Output: (grids, kept) — grids as in mesh_run_partials (tails hold
    whole runs), kept (time,) int32 post-dedup survivor counts."""
    from horaedb_tpu.ops import device_decode

    time_n = int(mesh.shape[TIME_AXIS])
    series_n = int(mesh.shape[SERIES_AXIS])
    gb = _series_block(num_groups, series_n)

    def shard_fn(cols, n_valid, leaf_consts, run_offsets, shift, lo,
                 seg_ids, total, bucket_ms):
        _check_block_is_one(cols[0])
        keys_s, gid, val_s, n_rows = device_decode.decode_rows_core(
            tuple(c[0] for c in cols), n_valid[0],
            tuple(c[0] for c in leaf_consts), run_offsets[0],
            key_slots=key_slots, num_pks=num_pks, group_pos=group_pos,
            val_slot=val_slot, leaf_prog=leaf_prog, route=route,
            num_runs=num_runs)
        p = downsample.window_local_partials(
            keys_s[ts_pos], gid, val_s,
            jnp.arange(num_groups, dtype=jnp.int32), shift[0], lo[0],
            total, bucket_ms[0], num_groups=num_groups,
            num_buckets=num_buckets, which=which)
        p = _series_slice(p, gb)
        state = _segmented_time_combine(p, seg_ids, time_n)
        return ({k: v[None] for k, v in state.items()}, n_rows[None])

    mapped = shard_map(
        shard_fn, mesh=mesh,
        in_specs=(P(TIME_AXIS, None), P(TIME_AXIS),
                  P(TIME_AXIS, None), P(TIME_AXIS, None),
                  P(TIME_AXIS), P(TIME_AXIS), P(TIME_AXIS), P(), P()),
        out_specs=(P(TIME_AXIS, SERIES_AXIS), P(TIME_AXIS)),
        check_vma=False,
    )
    return deviceprof.jit(mapped, name="mesh_decode_partials")


# ---- device-resident top-k score state -------------------------------------
#
# The egress-bounded top-k path (read._aggregate_topk_mesh): the round
# outputs above stay on the mesh; only a per-group score vector and the
# k winners' grid rows ever download.  Rankings by min/max/last are
# SELECTION ops, so accumulating their cells across rounds on device is
# exact — count/sum/avg rankings are additive and take the full-parts
# path instead (reason-counted).  These helpers are plain jitted jnp on
# the sharded round outputs; XLA's sharding propagation keeps the state
# series-partitioned (the round program owns the explicit collectives).
#
# Prefix slots (non-tails of the segmented scan) feed the state too:
# for selection ops a prefix's cells are a subset of its run's, so the
# duplicate combine is a no-op — no tail masking needed on device.

_TS_MIN = jnp.int32(-(2**31))


def mesh_score_init(num_groups: int, padded_buckets: int, by: str):
    """Identity-filled score state.  `padded_buckets` leaves one round
    -width of slack past the query's grid so per-slot dynamic slices
    never clamp (out-of-range buckets are empty cells by construction
    — window_local_partials drops rows past `total`)."""
    shape = (num_groups, padded_buckets)
    fill = {"min": jnp.finfo(jnp.float32).max,
            "max": -jnp.finfo(jnp.float32).max,
            "last": 0.0}[by]
    state = {"by": jnp.full(shape, jnp.float32(fill)),
             "has": jnp.zeros(shape, dtype=bool)}
    if by == "last":
        state["ts"] = jnp.full(shape, _TS_MIN)
    return state


@deviceprof.jit(static_argnames=("by",), donate_argnums=(0,))
def mesh_score_update(state: dict, by_grid, count_grid, last_ts, lo,
                      bucket_ms, *, by: str):
    """Fold one round's (time, groups, width) outputs into the score
    state, slot by slot in time order (the host fold's later-wins tie
    -break for `last`).  `last_ts` is None unless by == "last"; `lo`
    is the per-slot (time,) first-bucket offset."""
    width = by_grid.shape[2]

    def body(t, st):
        has_t = count_grid[t] > 0
        cur_by = jax.lax.dynamic_slice(
            st["by"], (0, lo[t]), (st["by"].shape[0], width))
        cur_has = jax.lax.dynamic_slice(
            st["has"], (0, lo[t]), (st["has"].shape[0], width))
        if by == "min":
            new_by = jnp.minimum(cur_by, by_grid[t])
        elif by == "max":
            new_by = jnp.maximum(cur_by, by_grid[t])
        else:  # last: select by global (range-relative) timestamp
            cur_ts = jax.lax.dynamic_slice(
                st["ts"], (0, lo[t]), (st["ts"].shape[0], width))
            cand_ts = jnp.where(has_t,
                                last_ts[t] + lo[t] * bucket_ms, _TS_MIN)
            take = cand_ts >= cur_ts
            new_by = jnp.where(take, by_grid[t], cur_by)
            st = dict(st)
            st["ts"] = jax.lax.dynamic_update_slice(
                st["ts"], jnp.where(take, cand_ts, cur_ts), (0, lo[t]))
        out = dict(st)
        out["by"] = jax.lax.dynamic_update_slice(st["by"], new_by,
                                                 (0, lo[t]))
        out["has"] = jax.lax.dynamic_update_slice(
            st["has"], cur_has | has_t, (0, lo[t]))
        return out

    return jax.lax.fori_loop(0, by_grid.shape[0], body, state)


@deviceprof.jit(static_argnames=("largest", "num_buckets"))
def mesh_score_finalize(state: dict, *, largest: bool, num_buckets: int):
    """(scores, has_any) per group — the ONLY full-group bytes the
    top-k path downloads.  Score formula mirrors combine_top_k's: the
    best count>0 cell of the ranking grid (NaN cells propagate, as in
    the host's np.max)."""
    by_grid = state["by"][:, :num_buckets]
    has = state["has"][:, :num_buckets]
    if largest:
        scores = jnp.where(has, by_grid, -jnp.inf).max(axis=1)
    else:
        scores = jnp.where(has, by_grid, jnp.inf).min(axis=1)
    return scores, has.any(axis=1)


# ---- additive (count/sum/avg) score state ----------------------------------
#
# Additive rankings cannot reuse the selection state above: a prefix
# slot's cells are NOT a subset of its run's — folding them would
# double-count — and f32 cell adds across rounds drift from the host
# control's f64 part-fold.  So the additive plane (a) folds TAIL slots
# only (the dispatcher passes the tails mask), and (b) keeps each cell
# as an exact (hi, lo) double-float pair (ops/topk.pair_add, the rollup
# plane's compensated discipline): while every add is provably exact
# AND f64-dense, host_f64_fold(same addends, same order) == hi + lo
# bit-exactly, so the ranking the host computes from the downloaded
# pair equals the mesh-off control's.  Any add that is not provably
# exact sets the sticky `lossy` scalar and the query downgrades to the
# full-parts path (reason-counted `additive_topk`) — never silently
# wrong.


def mesh_additive_init(num_groups: int, padded_buckets: int, by: str):
    """Zero-filled additive score state for ranking by `by` (count /
    sum / avg).  Same padded-bucket slack contract as mesh_score_init."""
    shape = (num_groups, padded_buckets)
    # distinct buffers per plane: the update donates the whole state,
    # and donation rejects aliased arguments
    z = lambda: jnp.zeros(shape, dtype=jnp.float32)
    state = {"has": jnp.zeros(shape, dtype=bool),
             "lossy": jnp.zeros((), dtype=bool)}
    if by in ("count", "avg"):
        state["cnt_hi"], state["cnt_lo"] = z(), z()
    if by in ("sum", "avg"):
        state["sum_hi"], state["sum_lo"] = z(), z()
    return state


@deviceprof.jit(static_argnames=("by",), donate_argnums=(0,))
def mesh_additive_update(state: dict, count_grid, sum_grid, tails, lo,
                         *, by: str):
    """Fold one round's (time, groups, width) outputs into the additive
    state — TAIL slots only (`tails` is the (time,) run-tail mask; a
    tail holds its whole run, prefixes would double-count).  Masked
    slots add exact zeros (a canonical-pair no-op) and are excluded
    from the lossy accounting."""
    width = count_grid.shape[2]
    planes = {"count": ("cnt",), "sum": ("sum",),
              "avg": ("cnt", "sum")}[by]
    grids = {"cnt": count_grid, "sum": sum_grid}

    def body(t, st):
        add = tails[t] & (count_grid[t] > 0)
        out = dict(st)
        for name in planes:
            hi = jax.lax.dynamic_slice(
                st[name + "_hi"], (0, lo[t]),
                (st[name + "_hi"].shape[0], width))
            lo_ = jax.lax.dynamic_slice(
                st[name + "_lo"], (0, lo[t]),
                (st[name + "_lo"].shape[0], width))
            h2, l2, exact = pair_add(
                hi, lo_, jnp.where(add, grids[name][t], 0.0))
            out[name + "_hi"] = jax.lax.dynamic_update_slice(
                st[name + "_hi"], h2, (0, lo[t]))
            out[name + "_lo"] = jax.lax.dynamic_update_slice(
                st[name + "_lo"], l2, (0, lo[t]))
            out["lossy"] = out["lossy"] | jnp.any(add & ~exact)
        cur_has = jax.lax.dynamic_slice(
            st["has"], (0, lo[t]), (st["has"].shape[0], width))
        out["has"] = jax.lax.dynamic_update_slice(
            st["has"], cur_has | add, (0, lo[t]))
        return out

    return jax.lax.fori_loop(0, count_grid.shape[0], body, state)


@deviceprof.jit(static_argnames=("by", "largest", "num_buckets"))
def mesh_additive_finalize(state: dict, *, by: str, largest: bool,
                           num_buckets: int):
    """Reduce the additive state to the download payload.

    count/sum: the per-group extreme cell as an exact (hi, lo) pair —
    normalized pairs order lexicographically, so the reduction is two
    masked maxes — O(groups) egress like the selection path.  avg
    needs a division the device cannot do bit-identically to the host,
    so it returns the full (groups, buckets) pair grids for the host's
    f64 sum/count divide — the one honestly O(groups × buckets) score
    egress (documented in docs/parallel.md).  `lossy` rides along."""
    has = state["has"][:, :num_buckets]
    out = {"has_any": has.any(axis=1), "lossy": state["lossy"]}
    if by == "avg":
        for name in ("cnt", "sum"):
            out[name + "_hi"] = state[name + "_hi"][:, :num_buckets]
            out[name + "_lo"] = state[name + "_lo"][:, :num_buckets]
        out["has"] = has
        return out
    name = {"count": "cnt", "sum": "sum"}[by]
    s_hi, s_lo = pair_max_normalized(
        state[name + "_hi"][:, :num_buckets],
        state[name + "_lo"][:, :num_buckets], has, axis=1,
        largest=largest)
    out["score_hi"], out["score_lo"] = s_hi, s_lo
    return out


@deviceprof.jit
def mesh_take_rows(grids: dict, idx):
    """Winner-row gather on device: (time, groups, width) round outputs
    sliced to the k winners' rows BEFORE download — the O(k x buckets
    x aggs) per-chip combine egress."""
    return {k: jnp.take(v, idx, axis=1) for k, v in grids.items()}
