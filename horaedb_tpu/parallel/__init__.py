"""Multi-chip execution: segment-axis mesh + shard_map scan programs.

The TPU-native replacement for the reference's cross-partition merge
(SortPreservingMergeExec under UnionExec, SURVEY.md section 2.5 P2/P3):
time segments are independent by construction (storage.rs:342-368 builds
one plan per segment), so segments ARE the shard axis.  Each chip
merge-dedups and partially aggregates its own segments; only the small
dense (group, bucket) grids cross chips, as psum/pmax/pmin collectives
over ICI — never row data.
"""

# Lazy exports (PEP 562): importing this package must not initialize
# the XLA backend (scan.py builds jnp constants at import), because
# multihost users have to call jax.distributed.initialize() FIRST —
# `from horaedb_tpu.parallel import multihost` stays backend-free.
_EXPORTS = {
    "segment_mesh": "horaedb_tpu.parallel.mesh",
    "scan_mesh": "horaedb_tpu.parallel.mesh",
    "default_scan_shape": "horaedb_tpu.parallel.mesh",
    "sharded_downsample_query": "horaedb_tpu.parallel.scan",
    "sharded_merge_dedup": "horaedb_tpu.parallel.scan",
    "sharded_remap_partials": "horaedb_tpu.parallel.scan",
    "mesh_run_partials": "horaedb_tpu.parallel.scan",
    "multihost": "horaedb_tpu.parallel.multihost",
}

__all__ = list(_EXPORTS)


def __getattr__(name: str):
    if name not in _EXPORTS:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}")
    import importlib

    mod = importlib.import_module(_EXPORTS[name])
    val = mod if name == "multihost" else getattr(mod, name)
    globals()[name] = val  # cache: next access skips __getattr__
    return val
