"""Multi-chip execution: segment-axis mesh + shard_map scan programs.

The TPU-native replacement for the reference's cross-partition merge
(SortPreservingMergeExec under UnionExec, SURVEY.md section 2.5 P2/P3):
time segments are independent by construction (storage.rs:342-368 builds
one plan per segment), so segments ARE the shard axis.  Each chip
merge-dedups and partially aggregates its own segments; only the small
dense (group, bucket) grids cross chips, as psum/pmax/pmin collectives
over ICI — never row data.
"""

from horaedb_tpu.parallel.mesh import segment_mesh
from horaedb_tpu.parallel.scan import (
    sharded_downsample_query,
    sharded_merge_dedup,
    sharded_remap_partials,
)

__all__ = ["segment_mesh", "sharded_downsample_query",
           "sharded_merge_dedup", "sharded_remap_partials"]
