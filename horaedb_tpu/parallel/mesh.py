"""Device mesh construction for the segment axis."""

from __future__ import annotations

from typing import Optional, Sequence

import jax
from jax.sharding import Mesh

from horaedb_tpu.common.error import ensure

SEGMENT_AXIS = "seg"


def segment_mesh(n_devices: Optional[int] = None,
                 devices: Optional[Sequence] = None) -> Mesh:
    """1-D mesh over the segment (time-window) axis.

    A single axis is the right topology for the scan workload: segments
    are embarrassingly parallel and only grid-sized aggregates cross the
    axis, so a v5e-8's ring handles the psum without any 2-D layout.
    """
    devs = list(devices) if devices is not None else jax.devices()
    if n_devices is not None:
        ensure(len(devs) >= n_devices,
               f"requested a {n_devices}-device mesh but only "
               f"{len(devs)} devices are available")
        devs = devs[:n_devices]
    import numpy as np

    return Mesh(np.array(devs), axis_names=(SEGMENT_AXIS,))
