"""Device mesh construction — THE module that declares mesh topology.

Two shapes live here:

  segment_mesh  the original 1-D mesh over the segment (time-window)
                axis, kept for the device_sort merge rounds and the
                multihost DCN tier;
  scan_mesh     the 2-D (time, series) mesh of the in-region scan
                ([scan.mesh]): plan segments shard along the `time`
                axis (one merge window per time slot, plan order),
                group/tsid blocks along the `series` axis.  The time
                axis carries the segmented-reduction combine
                (parallel/scan.py mesh_run_partials); the series axis
                divides the resident grid state and the per-chip
                combine egress by its size.

tools/lint.py enforces that Mesh/shard_map/NamedSharding construction
happens only under horaedb_tpu/parallel/ — mesh topology stays declared
in one place.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
from jax.sharding import Mesh

from horaedb_tpu.common.error import ensure

SEGMENT_AXIS = "seg"

# the 2-D scan mesh's axis names ([scan.mesh]; docs/parallel.md)
TIME_AXIS = "time"
SERIES_AXIS = "series"


def segment_mesh(n_devices: Optional[int] = None,
                 devices: Optional[Sequence] = None) -> Mesh:
    """1-D mesh over the segment (time-window) axis.

    A single axis is the right topology for the scan workload: segments
    are embarrassingly parallel and only grid-sized aggregates cross the
    axis, so a v5e-8's ring handles the psum without any 2-D layout.
    """
    devs = list(devices) if devices is not None else jax.devices()
    if n_devices is not None:
        ensure(len(devs) >= n_devices,
               f"requested a {n_devices}-device mesh but only "
               f"{len(devs)} devices are available")
        devs = devs[:n_devices]
    import numpy as np

    return Mesh(np.array(devs), axis_names=(SEGMENT_AXIS,))


def default_scan_shape(n_devices: int) -> tuple[int, int]:
    """Auto (time, series) factorization for `n` local devices: series
    gets 2 when it divides evenly past a 2x2 mesh, else 1 — the time
    axis (window parallelism) is where scan throughput scales, while
    the series axis only divides grid state and combine egress.
    Operators with huge-cardinality workloads raise [scan.mesh] series
    explicitly."""
    ensure(n_devices >= 1, "mesh needs at least one device")
    series = 2 if n_devices >= 4 and n_devices % 2 == 0 else 1
    return n_devices // series, series


def scan_mesh(time: int = 0, series: int = 0,
              devices: Optional[Sequence] = None) -> Mesh:
    """The 2-D (time, series) scan mesh ([scan.mesh]).

    `time`/`series` of 0 mean auto: use every local device under
    default_scan_shape's factorization (one axis given → the other is
    derived).  `series` must be a power of two — group spaces are
    padded to powers of two (read.py g_pad) and the series axis must
    divide them exactly."""
    import numpy as np

    devs = list(devices) if devices is not None else jax.devices()
    n = len(devs)
    if time == 0 and series == 0:
        time, series = default_scan_shape(n)
    elif time == 0:
        ensure(series > 0 and n % series == 0,
               f"[scan.mesh] series = {series} does not divide the "
               f"{n} local devices")
        time = n // series
    elif series == 0:
        ensure(time > 0 and n % time == 0,
               f"[scan.mesh] time = {time} does not divide the "
               f"{n} local devices")
        series = n // time
    ensure(time * series <= n,
           f"[scan.mesh] {time}x{series} mesh needs {time * series} "
           f"devices but only {n} are available")
    ensure(series & (series - 1) == 0,
           f"[scan.mesh] series = {series} must be a power of two "
           "(group spaces are padded to powers of two and the series "
           "axis must divide them)")
    devs = devs[: time * series]
    return Mesh(np.array(devs).reshape(time, series),
                axis_names=(TIME_AXIS, SERIES_AXIS))
