"""Predicate evaluation as device masks.

The reference pushes predicates into DataFusion's FilterExec + parquet
pruning (ref: src/storage/src/read.rs:459-475).  On TPU a filter never
reshapes data mid-pipeline — it produces a validity mask that downstream
segmented ops consume, so shapes stay static and XLA fuses the compare
chains into neighbouring kernels.

Predicates are small host-side trees.  Constants are translated to device
codes using the batch's ColumnEncodings (dictionary lookup / epoch shift)
at evaluation time; a constant absent from a dictionary yields an
all-false (Eq/In) or correct-by-order (range) mask via searchsorted.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional, Sequence, Union

import jax.numpy as jnp
import numpy as np

from horaedb_tpu.common.error import Error
from horaedb_tpu.ops.encode import ColumnEncoding, DeviceBatch

Predicate = Union["Eq", "Ne", "Lt", "Le", "Gt", "Ge", "In", "And", "Or",
                  "Not", "TimeRangePred"]


@dataclass(frozen=True)
class Eq:
    column: str
    value: Any


@dataclass(frozen=True)
class Ne:
    column: str
    value: Any


@dataclass(frozen=True)
class Lt:
    column: str
    value: Any


@dataclass(frozen=True)
class Le:
    column: str
    value: Any


@dataclass(frozen=True)
class Gt:
    column: str
    value: Any


@dataclass(frozen=True)
class Ge:
    column: str
    value: Any


@dataclass(frozen=True)
class In:
    column: str
    values: Sequence[Any]


@dataclass(frozen=True)
class And:
    children: Sequence[Predicate]


@dataclass(frozen=True)
class Or:
    children: Sequence[Predicate]


@dataclass(frozen=True)
class Not:
    child: Predicate


@dataclass(frozen=True)
class TimeRangePred:
    """[start, end) on a timestamp column — the scan's range predicate."""

    column: str
    start: int
    end: int


def _const_code_exact(enc: ColumnEncoding, value: Any):
    """Device constant for an equality compare; None if it cannot match."""
    if enc.kind == "numeric":
        return value
    if enc.kind == "offset":
        off = int(value) - enc.epoch
        return off if -(2**31) <= off < 2**31 else None
    if enc.kind == "dict":
        idx = np.searchsorted(enc.dictionary, value)
        if idx < len(enc.dictionary) and enc.dictionary[idx] == value:
            return int(idx)
        return None
    raise Error(f"unknown encoding kind: {enc.kind}")


def _const_code_lower(enc: ColumnEncoding, value: Any):
    """Device threshold t such that (col_value < value) == (code < t).

    Works for dict codes because np.unique codes are order-preserving.
    """
    if enc.kind == "numeric":
        return value
    if enc.kind == "offset":
        return int(np.clip(int(value) - enc.epoch, -(2**31), 2**31 - 1))
    if enc.kind == "dict":
        return int(np.searchsorted(enc.dictionary, value, side="left"))
    raise Error(f"unknown encoding kind: {enc.kind}")


def _const_code_upper(enc: ColumnEncoding, value: Any):
    """Device threshold t such that (col_value <= value) == (code < t)."""
    if enc.kind in ("numeric", "offset"):
        return _const_code_lower(enc, value)
    if enc.kind == "dict":
        return int(np.searchsorted(enc.dictionary, value, side="right"))
    raise Error(f"unknown encoding kind: {enc.kind}")


def canonical_predicate_key(pred: Optional[Predicate]) -> str:
    """Complete, deterministic identity string for a predicate tree.

    repr() is NOT sufficient: In.values may be a numpy array whose repr
    elides long contents, so two different predicates could collide.
    Every leaf value is rendered in full here.
    """
    if pred is None:
        return ""
    if isinstance(pred, (And, Or)):
        op = "and" if isinstance(pred, And) else "or"
        inner = " ".join(canonical_predicate_key(c) for c in pred.children)
        return f"({op} {inner})"
    if isinstance(pred, Not):
        return f"(not {canonical_predicate_key(pred.child)})"
    if isinstance(pred, In):
        vals = ",".join(repr(v) for v in list(pred.values))
        return f"(in {pred.column} [{vals}])"
    if isinstance(pred, TimeRangePred):
        return f"(range {pred.column} {pred.start} {pred.end})"
    return f"({type(pred).__name__.lower()} {pred.column} {pred.value!r})"


def predicate_columns(pred: Predicate) -> set[str]:
    """All column names a predicate references."""
    if isinstance(pred, (And, Or)):
        out: set[str] = set()
        for c in pred.children:
            out |= predicate_columns(c)
        return out
    if isinstance(pred, Not):
        return predicate_columns(pred.child)
    return {pred.column}


def leaf_mask_host(leaf: Predicate, col: np.ndarray) -> np.ndarray:
    """numpy bool mask of one comparison leaf over a host column — THE
    shared leaf evaluator for every host-side predicate path (parquet
    residual filters, post-merge host evaluation), so comparison
    semantics (including the [start, end) time-range convention) live in
    exactly one place."""
    if isinstance(leaf, Eq):
        return col == leaf.value
    if isinstance(leaf, Ne):
        return col != leaf.value
    if isinstance(leaf, Lt):
        return col < leaf.value
    if isinstance(leaf, Le):
        return col <= leaf.value
    if isinstance(leaf, Gt):
        return col > leaf.value
    if isinstance(leaf, Ge):
        return col >= leaf.value
    if isinstance(leaf, In):
        return np.isin(col, list(leaf.values))
    if isinstance(leaf, TimeRangePred):
        return (col >= leaf.start) & (col < leaf.end)
    raise Error(f"not a comparison leaf: {leaf!r}")


def to_arrow_expression(pred: Predicate, allowed: set[str]):
    expr, _key = to_arrow_expression_with_key(pred, allowed)
    return expr


def to_arrow_expression_with_key(pred: Predicate, allowed: set[str]):
    """Translate the safely-pushable part of a predicate tree into a
    pyarrow compute expression for Parquet row-group pruning + pre-merge
    row filtering (the analogue of the reference's ParquetExec pruning
    predicate, read.rs:442-465).

    Only predicates whose columns are ALL in `allowed` (the primary keys)
    may be pushed: dropping rows by PK removes whole groups, which is
    merge-safe; dropping by value columns would un-shadow older rows.

    The translation computes a sound UPPER BOUND of the predicate: in
    positive polarity an unpushable subterm relaxes to TRUE (so And drops
    it, and an Or containing one becomes unpushable), while under Not the
    child must translate exactly (widening under negation would wrongly
    narrow).  Returns (expr, key): expr is None when the bound
    degenerates to TRUE; key is a complete canonical string of the PUSHED
    subtree (scan-cache identity — pyarrow's own str() elides long isin
    lists, and keying the full predicate would duplicate cache entries
    for predicates sharing one pushed subtree).
    """
    import pyarrow.compute as pc

    TRUE = object()  # sentinel: "no constraint" in positive polarity

    def leaf(p: Predicate):
        if predicate_columns(p) - allowed:
            return None
        f = pc.field(p.column)
        if isinstance(p, Eq):
            return f == p.value
        if isinstance(p, Ne):
            return f != p.value
        if isinstance(p, Lt):
            return f < p.value
        if isinstance(p, Le):
            return f <= p.value
        if isinstance(p, Gt):
            return f > p.value
        if isinstance(p, Ge):
            return f >= p.value
        if isinstance(p, In):
            return f.isin(list(p.values))
        if isinstance(p, TimeRangePred):
            return (f >= p.start) & (f < p.end)
        return None

    def strict(p: Predicate):
        """Exact translation as (expr, key); None if not fully pushable."""
        if isinstance(p, (And, Or)):
            parts = [strict(c) for c in p.children]
            if any(x is None for x in parts):
                return None
            out, key = parts[0]
            for x, k in parts[1:]:
                out = (out & x) if isinstance(p, And) else (out | x)
                key = f"({'and' if isinstance(p, And) else 'or'} {key} {k})"
            return out, key
        if isinstance(p, Not):
            inner = strict(p.child)
            if inner is None:
                return None
            return ~inner[0], f"(not {inner[1]})"
        expr = leaf(p)
        return None if expr is None else (expr, repr(p))

    def upper(p: Predicate):
        """Upper bound as (expr, key); TRUE when nothing constrains."""
        if isinstance(p, And):
            parts = [x for x in (upper(c) for c in p.children) if x is not TRUE]
            if not parts:
                return TRUE
            out, key = parts[0]
            for x, k in parts[1:]:
                out = out & x
                key = f"(and {key} {k})"
            return out, key
        if isinstance(p, Or):
            parts = [upper(c) for c in p.children]
            if any(x is TRUE for x in parts):
                return TRUE  # one unconstrained branch unbounds the union
            out, key = parts[0]
            for x, k in parts[1:]:
                out = out | x
                key = f"(or {key} {k})"
            return out, key
        if isinstance(p, Not):
            inner = strict(p.child)  # exact required under negation
            if inner is None:
                return TRUE
            return ~inner[0], f"(not {inner[1]})"
        expr = leaf(p)
        return TRUE if expr is None else (expr, repr(p))

    result = upper(pred)
    if result is TRUE:
        return None, ""
    return result


def eval_predicate(pred: Predicate, batch: DeviceBatch) -> jnp.ndarray:
    """Evaluate to a (capacity,) bool mask (padding rows unconstrained —
    callers AND this with the batch validity mask).

    Residency-polymorphic: device-resident columns produce a fused
    device mask; host (numpy) windows — the default scan layout — stay
    entirely on host, so predicates never force a tunnel round trip."""
    xp = (np if isinstance(next(iter(batch.columns.values()), None),
                           np.ndarray) else jnp)
    if isinstance(pred, And):
        mask = xp.ones(batch.capacity, dtype=bool)
        for c in pred.children:
            mask = mask & eval_predicate(c, batch)
        return mask
    if isinstance(pred, Or):
        mask = xp.zeros(batch.capacity, dtype=bool)
        for c in pred.children:
            mask = mask | eval_predicate(c, batch)
        return mask
    if isinstance(pred, Not):
        return ~eval_predicate(pred.child, batch)

    col = batch.columns[pred.column]
    enc = batch.encodings[pred.column]

    if isinstance(pred, Eq):
        code = _const_code_exact(enc, pred.value)
        if code is None:
            return xp.zeros(batch.capacity, dtype=bool)
        return col == code
    if isinstance(pred, Ne):
        code = _const_code_exact(enc, pred.value)
        if code is None:
            return xp.ones(batch.capacity, dtype=bool)
        return col != code
    if isinstance(pred, In):
        mask = xp.zeros(batch.capacity, dtype=bool)
        for v in pred.values:
            code = _const_code_exact(enc, v)
            if code is not None:
                mask = mask | (col == code)
        return mask
    if isinstance(pred, Lt):
        return col < _const_code_lower(enc, pred.value)
    if isinstance(pred, Le):
        # dict codes have no "<=" constant: use the right-bisect threshold
        if enc.kind == "dict":
            return col < _const_code_upper(enc, pred.value)
        return col <= _const_code_upper(enc, pred.value)
    if isinstance(pred, Gt):
        if enc.kind == "dict":
            return col >= _const_code_upper(enc, pred.value)
        return col > _const_code_lower(enc, pred.value)
    if isinstance(pred, Ge):
        return col >= _const_code_lower(enc, pred.value)
    if isinstance(pred, TimeRangePred):
        lo = _const_code_lower(enc, pred.start)
        hi = _const_code_lower(enc, pred.end)
        return (col >= lo) & (col < hi)
    raise Error(f"unknown predicate: {pred!r}")
