"""Device-native decode: fuse sidecar decode + filter + bucket-aggregate
into ONE device dispatch (ROADMAP item 2).

The cold aggregate scan's measured wall is HOST work: the pipeline's
stall profile shows the device stage starved 137:1 on decode, and the
r6 ladder's 200M cold wall is GIL-bound Python/numpy encode/merge the
pipeline can only *overlap*, never shrink.  The sidecar already stores
columns in a device-shaped layout (int32 dict codes, int32 epoch
offsets, raw float32 — storage/sidecar.py), yet the host still k-way
-merges, windows, uniques and stacks them before the device ever runs.

This module moves that whole chain onto the accelerator.  For an
eligible aggregate plan, an `EncodedSegment`'s encoded buffers upload
RAW (pad + device_put — memcpy-shaped host work) and one jitted
program does:

  leaf filter   — the plan's pushed PK-leaf conjunction evaluated in
                  ENCODED space (constants pre-translated host-side via
                  the same ops.filter helpers the host mask uses);
  merge-dedup   — lax.sort by (valid, pk codes..., seq, row) and a
                  keep-last-of-PK-run mask: the device twin of the host
                  k-way merge + `_host_dedup_keep`, with dropped rows
                  MASKED (gid = -1), never compacted, so shapes stay
                  static.  The row-index tiebreak reproduces the host
                  merge's stable ordering bit-for-bit, which is what
                  keeps f32 per-cell accumulation order — and therefore
                  the grids' bytes — identical to the host path;
  aggregate     — ops.downsample.window_local_partials over the sorted,
                  masked rows: the SAME partial-grid kernel the host
                  window path vmaps, so the emitted part has the exact
                  conventions storage/combine.py folds.

The output is one per-segment part `(group_values, bucket_lo, grids)`
— the shape `read._flush_window_batch` produces — so everything
downstream (sparse/dense combine, top-k pushdown, the delta-summation
parts memo) is untouched and the host-decode path remains the
bit-identity control ([scan.decode] mode = "host"; the seeded chaos
suite byte-compares the two, tests/test_device_decode.py).

Ineligible plans/segments fall back to host decode with an explicit
per-reason counter (`scan_decode_fallback_total{reason=}`) so a
silently-ineligible plan is visible instead of quietly slow
(docs/observability.md).  The Pallas partials kernel
(ops/pallas_kernels.py) slots in behind the same
HORAEDB_DOWNSAMPLE_IMPL knob, with its failure guard reporting
"no TPU" and "kernel bug" as distinct reasons instead of a bare
try/except.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

from horaedb_tpu.common import deviceprof
from horaedb_tpu.ops import downsample
from horaedb_tpu.ops import filter as filter_ops
from horaedb_tpu.ops import merge as merge_ops
from horaedb_tpu.ops.filter import (
    _const_code_exact,
    _const_code_lower,
    _const_code_upper,
)
from horaedb_tpu.utils import registry, trace_add

logger = logging.getLogger(__name__)

# every way a plan or segment can decline the device-decode path, so
# operators can tell "misconfigured dashboard" from "unsupported data"
# (docs/observability.md).  pallas_* reasons come from the kernel-impl
# guard (see use_pallas_partials): off-TPU interpret failures and real
# kernel bugs must not be one indistinguishable except clause.
FALLBACK_REASONS = (
    "mesh",            # meshed scans keep their own round scheduler
    "append_mode",     # BytesMerge needs exact Arrow bytes
    "no_sidecar",      # plan can't serve from sidecars at all
    "predicate",       # predicate not a device-evaluable PK conjunction
    "parquet",         # this segment fell back to a parquet read
    "encoding",        # a column's encoding has no device decode
    "dtype",           # a column's dtype isn't the device layout
    "budget",          # segment exceeds [scan.decode] max_upload_bytes
    "range",           # epoch-to-range shift overflows int32
    "pallas_no_tpu",   # pallas impl failed off-TPU (interpret mode)
    "pallas_error",    # pallas impl failed ON TPU — a real kernel bug
    "kway_runs",       # multi-run segment declined the k-way merge
                       # (run boundaries unknown / runs not per-run
                       # sorted / too many runs) — the dispatch still
                       # decodes on device but pays the full lax.sort
)

_FALLBACKS = registry.counter(
    "scan_decode_fallback_total",
    "aggregate segments/plans that fell back to host decode, by reason "
    "— a silently-ineligible plan shows up here instead of being "
    "quietly slow")
_FALLBACK_CHILDREN = {r: _FALLBACKS.labels(reason=r)
                      for r in FALLBACK_REASONS}

# compaction-aware sort-free routing (ROADMAP item 2c): per-segment
# routed-vs-sorted evidence for the fused dispatch's O(n log n) device
# sort — the steady-state post-compaction scan should read ~all
# "compacted"
_SORT_SKIPPED = {
    route: registry.counter(
        "scan_decode_sort_skipped_total",
        "fused decode dispatches that skipped the device lax.sort: "
        "compacted = single-run segment, (pk, seq)-sorted by "
        "construction (no host check either); checked = the one-pass "
        "host sortedness check proved the concatenated runs sorted; "
        "kway = multi-run interleaved segment merged on device by the "
        "presorted-run k-way merge (ops/merge.kway_merge_perm) instead "
        "of the full sort"
    ).labels(route=route)
    for route in ("compacted", "checked", "kway")
}
_SORT_RAN = registry.counter(
    "scan_decode_sorted_total",
    "fused decode dispatches that paid the device lax.sort "
    "(multi-run interleaved segments)")


def note_fallback(reason: str) -> None:
    child = _FALLBACK_CHILDREN.get(reason)
    if child is None:  # unknown reasons still count, labeled verbatim
        child = _FALLBACKS.labels(reason=reason)
        _FALLBACK_CHILDREN[reason] = child
    child.inc()
    trace_add(f"decode_fallback_{reason}", 1)


# ---------------------------------------------------------------------------
# leaf compilation: predicate leaves -> encoded-space ops
# ---------------------------------------------------------------------------

# opcodes are STATIC (they select the compare emitted at trace time);
# constants are traced int32 so varied dashboards share one program
_OP_EQ, _OP_LT, _OP_LE, _OP_GT, _OP_GE, _OP_RANGE, _OP_IN = range(7)
_EDGE_NAMES = {_OP_LT: "lt", _OP_LE: "le", _OP_GT: "gt", _OP_GE: "ge"}

# an In leaf beyond this many resolved codes would trace a (capacity x
# k) compare — fall back to host decode instead of trading HBM for it
_IN_MAX_CODES = 64

# beyond this many presorted runs the k-way merge tree's log2(k) levels
# of binary searches stop beating the full bitonic sort — decline to
# the sort route (counted reason="kway_runs") instead
_KWAY_MAX_RUNS = 64


class _EmptyMatch(Exception):
    """A leaf provably matches nothing (Eq/In constant absent from the
    dictionary): the segment contributes an empty part, no dispatch."""


_I32_LO, _I32_HI = -(2**31), 2**31 - 1


def _exact_i32(c) -> Optional[int]:
    """An equality constant as int32, or None when it cannot match any
    code (out-of-range) — the host mask's numpy compare upcasts and
    yields all-False there; int32-casting unguarded would wrap (old
    numpy) or raise OverflowError (numpy >= 1.24)."""
    c = int(c)
    return c if _I32_LO <= c <= _I32_HI else None


def _thresh_i32(c) -> int:
    """A comparison threshold clamped to int32.  Callers must first
    resolve the out-of-range edges where a clamp would NOT compare
    identically (a raw int32 column may legitimately hold I32_LO or
    I32_HI — see _numeric_edge): after that, clamping is exact."""
    return int(np.clip(int(c), _I32_LO, _I32_HI))


# what an out-of-int32 numeric threshold means for each comparison —
# the host mask compares unclamped via numpy upcast, so a below-range
# `col > c` is a TAUTOLOGY (keep every row, incl. a raw code of
# I32_LO) and an above-range `col >= c` matches NOTHING; a clamp alone
# would wrongly include/exclude codes equal to the int32 extremes.
# Values: "taut" = drop the leaf (no constraint), "empty" = the leaf
# provably matches nothing, None = in range (clamp is exact).
def _numeric_edge(op: int, t: int) -> Optional[str]:
    if t < _I32_LO:
        return {"lt": "empty", "le": "empty",
                "gt": "taut", "ge": "taut"}[_EDGE_NAMES[op]]
    if t > _I32_HI:
        return {"lt": "taut", "le": "taut",
                "gt": "empty", "ge": "empty"}[_EDGE_NAMES[op]]
    return None


def leaf_shape_supported(leaves) -> bool:
    """Plan-level check: every pushed leaf is a type the device program
    can evaluate.  Mirrors parquet_io.conjunct_leaves_ex's leaf list;
    constants translate per segment (they need the encodings)."""
    F = filter_ops
    for leaf in leaves or []:
        if not isinstance(leaf, (F.Eq, F.Lt, F.Le, F.Gt, F.Ge, F.In,
                                 F.TimeRangePred)):
            return False
        if isinstance(leaf, F.In) and len(list(leaf.values)) > _IN_MAX_CODES:
            return False
    return True


def compile_leaves(leaves, encodings) -> tuple[tuple, tuple]:
    """Translate a leaf conjunction into ((column, opcode), ...) static
    program + per-leaf int32 constant arrays, in ENCODED space — the
    exact semantics of ops.filter.eval_predicate's host mask (including
    the dict-code Le/Gt asymmetry), computed with the same helpers.

    Raises _EmptyMatch when a leaf provably matches nothing and
    ValueError when a leaf/encoding combination has no device form
    (caller counts reason="predicate"/"encoding")."""
    F = filter_ops
    prog: list = []
    consts: list = []
    for leaf in leaves or []:
        enc = encodings.get(leaf.column)
        if enc is None:
            raise ValueError(f"leaf column {leaf.column!r} missing")
        if isinstance(leaf, F.Eq):
            c = _const_code_exact(enc, leaf.value)
            c = None if c is None else _exact_i32(c)
            if c is None:
                raise _EmptyMatch
            prog.append((leaf.column, _OP_EQ))
            consts.append(np.asarray([c], dtype=np.int32))
        elif isinstance(leaf, F.In):
            codes = sorted(ci for ci in (
                _exact_i32(c) for c in (_const_code_exact(enc, v)
                                        for v in leaf.values)
                if c is not None) if ci is not None)
            if not codes:
                raise _EmptyMatch
            prog.append((leaf.column, _OP_IN))
            consts.append(np.asarray(codes, dtype=np.int32))
        elif isinstance(leaf, (F.Lt, F.Le, F.Gt, F.Ge)):
            # dict thresholds are searchsorted indices (always in
            # range); numeric/offset map exactly as eval_predicate's
            # host mask, with numeric out-of-int32 edges resolved to
            # tautology / empty-match BEFORE the clamp (a raw int32
            # column may hold the int32 extremes)
            if enc.kind == "dict":
                if isinstance(leaf, F.Lt):
                    op, t = _OP_LT, _const_code_lower(enc, leaf.value)
                elif isinstance(leaf, F.Le):
                    op, t = _OP_LT, _const_code_upper(enc, leaf.value)
                elif isinstance(leaf, F.Gt):
                    op, t = _OP_GE, _const_code_upper(enc, leaf.value)
                else:
                    op, t = _OP_GE, _const_code_lower(enc, leaf.value)
            else:
                if isinstance(leaf, F.Lt):
                    op, t = _OP_LT, _const_code_lower(enc, leaf.value)
                elif isinstance(leaf, F.Le):
                    op, t = _OP_LE, _const_code_upper(enc, leaf.value)
                elif isinstance(leaf, F.Gt):
                    op, t = _OP_GT, _const_code_lower(enc, leaf.value)
                else:
                    op, t = _OP_GE, _const_code_lower(enc, leaf.value)
                if enc.kind == "numeric":
                    edge = _numeric_edge(op, int(t))
                    if edge == "empty":
                        raise _EmptyMatch
                    if edge == "taut":
                        continue  # no constraint: drop the leaf
            prog.append((leaf.column, op))
            consts.append(np.asarray([_thresh_i32(t)], dtype=np.int32))
        elif isinstance(leaf, F.TimeRangePred):
            lo_t = _const_code_lower(enc, leaf.start)
            hi_t = _const_code_lower(enc, leaf.end)
            lo_edge = hi_edge = None
            if enc.kind == "numeric":
                lo_edge = _numeric_edge(_OP_GE, int(lo_t))
                hi_edge = _numeric_edge(_OP_LT, int(hi_t))
            if lo_edge == "empty" or hi_edge == "empty":
                raise _EmptyMatch
            if lo_edge == "taut" and hi_edge == "taut":
                continue
            if lo_edge == "taut":
                prog.append((leaf.column, _OP_LT))
                consts.append(np.asarray([_thresh_i32(hi_t)],
                                         dtype=np.int32))
            elif hi_edge == "taut":
                prog.append((leaf.column, _OP_GE))
                consts.append(np.asarray([_thresh_i32(lo_t)],
                                         dtype=np.int32))
            else:
                prog.append((leaf.column, _OP_RANGE))
                consts.append(np.asarray(
                    [_thresh_i32(lo_t), _thresh_i32(hi_t)],
                    dtype=np.int32))
        else:
            raise ValueError(f"unsupported leaf {type(leaf).__name__}")
    return tuple(prog), tuple(consts)


# ---------------------------------------------------------------------------
# the fused program
# ---------------------------------------------------------------------------


def _leaf_mask(col, op: int, c):
    if op == _OP_EQ:
        return col == c[0]
    if op == _OP_LT:
        return col < c[0]
    if op == _OP_LE:
        return col <= c[0]
    if op == _OP_GT:
        return col > c[0]
    if op == _OP_GE:
        return col >= c[0]
    if op == _OP_RANGE:
        return (col >= c[0]) & (col < c[1])
    # _OP_IN: small resolved-code set, compare-broadcast then any
    return (col[:, None] == c[None, :]).any(axis=1)


def _lex_sorted_np(keys: list) -> bool:
    """Host twin of read._is_lex_sorted over unpadded encoded columns:
    one vectorized compare pass decides whether the device program can
    skip its O(n log n) sort entirely — single-SST/post-compaction
    segments (the steady-state cold-scan shape) arrive (pk, seq)-sorted
    already, exactly the check the host k-way merge starts with."""
    n = len(keys[0])
    if n <= 1:
        return True
    still_equal = np.ones(n - 1, dtype=bool)
    for c in keys:
        if bool(np.any(still_equal & (c[:-1] > c[1:]))):
            return False
        still_equal &= c[:-1] == c[1:]
        if not still_equal.any():
            return True
    return True


def decode_rows_core(cols: tuple, n_valid, leaf_consts: tuple,
                     run_offsets, *, key_slots: tuple, num_pks: int,
                     group_pos: int, val_slot: int, leaf_prog: tuple,
                     route: str, num_runs: int):
    """The traced decode→filter→merge→dedup body, shared by the single
    -device fused dispatch below and the mesh's per-slot program
    (parallel/scan.mesh_decode_partials).  Returns (keys_s, gid,
    val_s, n_rows): rows in (pk, seq)-sorted order with dropped rows
    masked to gid = -1 — the exact shape window_local_partials expects
    (ts rides in keys_s[ts_pos]).

    `route` picks how rows reach sorted order:
      presorted — they already are (host-checked / single run);
      kway      — merge the `num_runs` presorted runs bounded by
                  `run_offsets` on device (ops/merge.kway_merge_perm),
                  then stably sink filter-failed rows so the valid
                  prefix is BIT-identical to the sort route's;
      sorted    — the full variadic device sort (the counted fallback).
    """
    cap = cols[0].shape[0]
    iota = jnp.arange(cap, dtype=jnp.int32)
    valid = iota < jnp.asarray(n_valid, jnp.int32)
    for (slot, op), c in zip(leaf_prog, leaf_consts):
        valid = valid & _leaf_mask(cols[slot], op, c)

    if route == "presorted":
        # rows already arrive (pk, seq)-sorted (host-checked, the
        # single-SST/post-compaction shape): the run-boundary masks
        # below work in place.  Leaf-failed rows cannot split a run —
        # prune leaves are PK-only, so an equal-PK run passes or fails
        # as a whole — and padding rows are trailing.
        valid_s = valid
        keys_s = tuple(cols[i] for i in key_slots)
        val_s = cols[val_slot]
    elif route == "kway":
        # merge the presorted runs by (padding, pk..., seq, row): the
        # padding bit keeps the trailing pad zone (its own run) last
        # without perturbing within-run order, and strict/leq counting
        # inside the merge supplies the row tiebreak.  Filter-failed
        # rows then sink behind the valid prefix via a stable
        # partition, so the prefix — the only thing the grids see —
        # is bit-identical to the sort route's (~valid, keys, row)
        # order.
        pad_bit = (~(iota < jnp.asarray(n_valid, jnp.int32))) \
            .astype(jnp.int32)
        mkeys = (pad_bit,) + tuple(cols[i]
                                   for i in key_slots[:num_pks + 1])
        perm = merge_ops.kway_merge_perm(mkeys, run_offsets,
                                         num_runs=num_runs)
        valid_m = valid[perm]
        vpos = jnp.cumsum(valid_m.astype(jnp.int32))
        n_ok = vpos[-1]
        ipos = jnp.cumsum((~valid_m).astype(jnp.int32))
        pos = jnp.where(valid_m, vpos - 1, n_ok + ipos - 1)
        part = jnp.zeros(cap, dtype=jnp.int32).at[pos].set(perm)
        valid_s = valid[part]
        keys_s = tuple(cols[i][part] for i in key_slots)
        val_s = cols[val_slot][part]
    else:
        # sort by (invalid, pks..., seq, ..., row): invalid rows sink
        # as a block; the row index makes the key total, so equal-
        # (pk, seq) duplicates keep their concatenation order — the
        # host radix merge's stability contract (_plan_merge_perm)
        operands = [(~valid).astype(jnp.int32)] \
            + [cols[i] for i in key_slots] + [iota, cols[val_slot]]
        n_keys = 2 + len(key_slots)
        sorted_ops = merge_ops.lex_sort(tuple(operands), num_keys=n_keys)
        valid_s = sorted_ops[0] == 0
        keys_s = sorted_ops[1:1 + len(key_slots)]
        val_s = sorted_ops[-1]
    # keep-last per PK run among surviving rows (_host_dedup_keep):
    # a row survives iff valid and (last row | next row invalid | any
    # pk differs from the next row).  Run boundaries compare the PK
    # keys ONLY — seq orders within a run, it never splits one.
    differs_next = jnp.zeros(cap - 1, dtype=bool)
    for c in keys_s[:num_pks]:
        differs_next = differs_next | (c[:-1] != c[1:])
    kept = valid_s & jnp.concatenate(
        [differs_next | ~valid_s[1:], jnp.ones(1, dtype=bool)])

    gid = jnp.where(kept, keys_s[group_pos], jnp.int32(-1))
    n_rows = jnp.sum(kept.astype(jnp.int32))
    return keys_s, gid, val_s, n_rows


@deviceprof.jit(static_argnames=(
    "key_slots", "num_pks", "group_pos", "ts_pos", "val_slot",
    "leaf_prog", "g_pad", "width", "which", "use_pallas", "route",
    "num_runs"))
def _decode_aggregate_jit(cols: tuple, n_valid, leaf_consts: tuple,
                          shift, lo, total, bucket_ms, run_offsets, *,
                          key_slots: tuple, num_pks: int,
                          group_pos: int, ts_pos: int,
                          val_slot: int, leaf_prog: tuple,
                          g_pad: int, width: int, which: tuple,
                          use_pallas: bool, route: str = "sorted",
                          num_runs: int = 0):
    """THE fused dispatch: encoded columns in, partial grids out.

    `cols` is the tuple of uploaded int32 code columns (pad capacity);
    `key_slots` indexes the sort keys into it — the first `num_pks`
    are the PK code columns, then seq, then any non-PK group/ts column
    (appended AFTER seq so they cannot perturb the dedup order; with
    (pk, seq) effectively unique they only ride along to come back
    sorted).  `group_pos`/`ts_pos` locate the group/ts columns inside
    the sorted key outputs; `val_slot` indexes the f32 value column
    (carried, not a key).  `leaf_prog` is the static (column-slot,
    opcode) program from compile_leaves with `leaf_consts` its traced
    constants.  Row ordering/dedup semantics live in decode_rows_core
    (shared with the mesh round program).

    Dropped rows (padding, leaf-filtered, dup-shadowed) are masked to
    gid = -1, never compacted — static shapes, no host round trip.
    Returns ({partial grids}, kept_rows)."""
    cap = cols[0].shape[0]
    keys_s, gid, val_s, n_rows = decode_rows_core(
        cols, n_valid, leaf_consts, run_offsets, key_slots=key_slots,
        num_pks=num_pks, group_pos=group_pos, val_slot=val_slot,
        leaf_prog=leaf_prog, route=route, num_runs=num_runs)
    ts_s = keys_s[ts_pos]
    if use_pallas:
        from horaedb_tpu.ops.pallas_kernels import pallas_window_partials

        shift32 = jnp.asarray(shift, jnp.int32)
        lo32 = jnp.asarray(lo, jnp.int32)
        bucket32 = jnp.asarray(bucket_ms, jnp.int32)
        gid = jnp.where(
            (ts_s + shift32) // bucket32
            < jnp.asarray(total, jnp.int32), gid, -1)
        grids = pallas_window_partials(
            ts_s + shift32 - lo32 * bucket32, gid, val_s, cap, bucket32,
            num_groups=g_pad, num_buckets=width, which=which,
            interpret=jax.devices()[0].platform != "tpu")
    else:
        grids = downsample.window_local_partials(
            ts_s, gid, val_s, jnp.arange(g_pad, dtype=jnp.int32),
            shift, lo, total, bucket_ms, num_groups=g_pad,
            num_buckets=width, which=which)
    return grids, n_rows


# ---------------------------------------------------------------------------
# dispatch / finalize wrappers
# ---------------------------------------------------------------------------


@dataclass
class DevicePart:
    """A segment's finished aggregate partial from the device-decode
    path, shaped to coexist with DeviceBatch windows in a segment's
    `windows` list (n_valid/nbytes feed the same pipeline accounting).
    `part` is (group_values, bucket_lo, grids) — exactly what
    `_flush_window_batch` emits — or None when the segment provably
    contributes nothing (an Eq/In constant absent from the
    dictionary)."""

    part: Optional[tuple]
    n_valid: int   # post-dedup surviving rows (ops-metric parity)
    nbytes: int    # host bytes of the downloaded grids


class DecodeDispatch:
    """One segment's in-flight fused dispatch: the jit call has been
    issued (device work runs async); finalize() downloads the grids and
    shapes the part.  Split so the pipeline's decode stage can dispatch
    segment k+1's upload while segment k's kernel still runs."""

    __slots__ = ("outs", "n_rows", "values", "lo", "w_eff", "bucket_ms",
                 "t_dispatch", "upload_bytes", "src_rows")

    def __init__(self, outs, n_rows, values, lo, w_eff, bucket_ms,
                 t_dispatch, upload_bytes, src_rows):
        self.outs = outs
        self.n_rows = n_rows
        self.values = values
        self.lo = lo
        self.w_eff = w_eff
        self.bucket_ms = bucket_ms
        self.t_dispatch = t_dispatch
        self.upload_bytes = upload_bytes
        self.src_rows = src_rows

    def finalize(self) -> DevicePart:
        t0 = time.perf_counter()
        g = len(self.values)
        # the full (g_pad, width) grids cross the device boundary here
        # (np.asarray downloads the whole buffer before the slice) —
        # the d2h charge counts what moved, not what was kept
        d2h_bytes = sum(int(getattr(v, "nbytes", 0))
                        for v in self.outs.values())
        # mirror _flush_window_batch's emission exactly: slice to the
        # real group count and the query-clipped width, then re-base
        # window-local last_ts to range_start-relative int64.  The
        # slices COPY (ascontiguousarray): a view would pin the full
        # (g_pad, width) download while nbytes counted only the slice
        # — the PartsMemo views-pin-bases defect, not repeated here
        grids = {k: np.ascontiguousarray(np.asarray(v)[:g, :self.w_eff])
                 for k, v in self.outs.items()}
        # the asarray wait IS the device execution for this dispatch
        # (the jit call returned immediately; this synced)
        deviceprof.observe_exec("_decode_aggregate_jit",
                                time.perf_counter() - t0)
        deviceprof.charge_transfer("d2h", d2h_bytes)
        if "last_ts" in grids:
            lt = grids["last_ts"].astype(np.int64)
            grids["last_ts"] = np.where(
                grids["count"] > 0, lt + self.lo * self.bucket_ms, lt)
        n_rows = int(self.n_rows)
        nbytes = sum(int(a.nbytes) for a in grids.values())
        part = DevicePart(part=(self.values, self.lo, grids),
                          n_valid=n_rows, nbytes=nbytes)
        observe_decode_stage(self.t_dispatch
                             + (time.perf_counter() - t0),
                             rows=self.src_rows,
                             nbytes=self.upload_bytes)
        return part


# stage attribution twins ride the same labeled families as every other
# plan stage (docs/observability.md); read.py's plan_stage_snapshot
# includes "device_decode" so bench diffs pick it up
_STAGE_SECONDS = registry.histogram(
    "scan_stage_seconds", "wall seconds per merge-scan plan stage"
).labels(stage="device_decode")
_STAGE_ROWS = registry.counter(
    "scan_stage_rows_total", "rows entering each plan stage"
).labels(stage="device_decode")
_STAGE_BYTES = registry.counter(
    "scan_stage_bytes_total", "bytes entering each plan stage"
).labels(stage="device_decode")


def observe_decode_stage(seconds: float, rows: int, nbytes: int) -> None:
    _STAGE_SECONDS.observe(seconds)
    trace_add("stage_device_decode_ms", seconds * 1e3)
    if rows:
        _STAGE_ROWS.inc(rows)
        trace_add("stage_device_decode_rows", rows)
    if nbytes:
        _STAGE_BYTES.inc(nbytes)
        trace_add("stage_device_decode_bytes", nbytes)


def use_pallas_partials() -> bool:
    """Whether the fused dispatch should route its aggregate through
    the Pallas partials kernel — the same measured-before-adoption knob
    as the fused single-shot aggregate (HORAEDB_DOWNSAMPLE_IMPL)."""
    return downsample.downsample_impl() == "pallas"


def classify_pallas_failure() -> str:
    """Distinguish 'this host has no TPU' (interpret-mode gaps, an
    environment fact) from 'the kernel is broken on real hardware' (a
    bug CI must surface) — the two must not share one except clause."""
    try:
        on_tpu = jax.devices()[0].platform == "tpu"
    except Exception:  # noqa: BLE001 — no backend at all counts as no TPU
        on_tpu = False
    return "pallas_error" if on_tpu else "pallas_no_tpu"


@dataclass
class DecodePlan:
    """One segment's fused dispatch, PLANNED but not yet on the device:
    all gates passed, leaves compiled, routing decided, geometry
    computed — no upload issued.  `execute_plan` runs it standalone on
    the default device; the mesh scheduler instead groups compatible
    plans (same `static_key`) into one sharded per-round program
    (read._run_mesh_decode_round), so decode shards along the time
    axis with the aggregation instead of serializing ahead of it."""

    es: object
    cap: int
    shift: int
    lo: int
    local_ok: bool
    use_width: int
    w_eff: int
    g: int
    g_pad: int
    values: object            # the group dictionary (host array)
    upload_names: list
    key_slots: tuple
    num_pks: int
    group_pos: int
    ts_pos: int
    val_slot: int
    leaf_prog: tuple
    consts: tuple             # host int32 arrays, one per leaf
    route: str                # "presorted" | "kway" | "sorted"
    run_offsets: Optional[np.ndarray]
    num_runs: int
    which: tuple
    bucket_ms: int
    num_buckets: int

    @property
    def n_valid(self) -> int:
        # windows-list accounting parity (DeviceBatch/DevicePart ride
        # the same lists): source rows, pre-filter/dedup
        return self.es.n

    def static_key(self) -> tuple:
        """Everything that must match for two plans to share one
        compiled mesh-round program (traced-constant SHAPES included:
        leaf const arrays stack across slots)."""
        return (self.key_slots, self.num_pks, self.group_pos,
                self.ts_pos, self.val_slot, self.leaf_prog,
                tuple(len(c) for c in self.consts), self.route,
                self.num_runs, self.local_ok, len(self.upload_names),
                self.which)


def plan_dispatch(es, spec, pk_names: list, seq_name: str,
                  leaves, max_bytes: int, width: int,
                  pad_capacity) -> "DecodePlan | DevicePart | str":
    """Validate one EncodedSegment against the fused program's layout
    and plan its dispatch WITHOUT touching the device.  Returns a
    DecodePlan (ready to execute or to join a mesh round), a DevicePart
    (provably-empty segment, no dispatch), or a fallback reason string
    (the caller counts it and takes the host path)."""
    encs = es.encodings
    # layout gates, cheapest first; reasons mirror FALLBACK_REASONS
    for name in (spec.group_col, spec.ts_col, spec.value_col, seq_name,
                 *pk_names):
        if name not in es.columns:
            return "encoding"
    ts_enc = encs[spec.ts_col]
    if ts_enc.kind not in ("offset", "numeric"):
        return "encoding"
    g_enc = encs[spec.group_col]
    if g_enc.kind != "dict" or g_enc.dictionary is None \
            or len(g_enc.dictionary) == 0:
        return "encoding"  # codes must BE dense ids over a known space
    if es.columns[spec.value_col].dtype != np.float32:
        return "dtype"
    for name in (spec.ts_col, seq_name, *pk_names):
        if es.columns[name].dtype != np.int32:
            return "dtype"
    shift = int(ts_enc.epoch) - spec.range_start
    if abs(shift) >= 2**31:
        return "range"
    cap = pad_capacity(es.n)

    try:
        prog, consts = compile_leaves(leaves, encs)
    except _EmptyMatch:
        return DevicePart(part=None, n_valid=0, nbytes=0)
    except (ValueError, OverflowError):
        return "predicate"

    # upload slots: pk codes, then seq (the dedup order), then any
    # non-PK group/ts column appended AFTER seq — sort keys past
    # (pk, seq, ..., row) refine an effectively-total order, so they
    # ride along only to come back in sorted row order; the value
    # column and any leaf-only columns complete the upload set
    key_names = list(pk_names)
    key_names.append(seq_name)
    for nm in (spec.group_col, spec.ts_col):
        if nm not in key_names:
            key_names.append(nm)
    slot_of: dict = {}
    upload_names: list = []
    for nm in key_names + [spec.value_col] \
            + [c for c, _op in prog]:
        if nm not in slot_of:
            slot_of[nm] = len(upload_names)
            upload_names.append(nm)
    # HBM admission over the ACTUAL upload set (non-PK group/ts and
    # leaf-only columns included — undercounting would admit a
    # segment over budget and OOM the device instead of falling back)
    if cap * 4 * len(upload_names) > max_bytes:
        return "budget"

    # compaction-aware sort-free routing: a single-run segment (the
    # post-compaction steady state) is (pk, seq)-sorted by
    # construction — both write paths sort before the SST put and
    # compaction emits merge-sorted — so it routes sort-free without
    # even the one-pass host check; multi-run segments pay the check;
    # interleaved multi-run segments with known per-run boundaries
    # k-way-merge the presorted runs on device (row tiebreak
    # preserved, grids byte-identical); only segments neither route
    # admits pay the device lax.sort, counted reason="kway_runs".
    route = "sorted"
    run_offsets = None
    num_runs = 0
    key_arrs = [es.columns[nm] for nm in pk_names] \
        + [es.columns[seq_name]]
    if es.source_runs == 1:
        route = "presorted"
        _SORT_SKIPPED["compacted"].inc()
    elif _lex_sorted_np(key_arrs):
        route = "presorted"
        _SORT_SKIPPED["checked"].inc()
    else:
        rl = getattr(es, "run_lengths", None)
        offs = None
        if rl and 1 < len(rl) <= _KWAY_MAX_RUNS \
                and sum(rl) == es.n:
            offs = np.cumsum(np.asarray((0,) + tuple(rl),
                                        dtype=np.int64))
            if not merge_ops.runs_lex_sorted_np(key_arrs, offs):
                offs = None
        if offs is not None:
            route = "kway"
            # runs + the trailing pad zone as its own run, padded to a
            # power of two with empty runs (static merge-tree depth)
            num_runs = 1 << max(1, int(len(rl))).bit_length()
            run_offsets = np.full(num_runs + 1, cap, dtype=np.int32)
            run_offsets[:len(offs)] = offs
            run_offsets[len(rl)] = es.n  # real runs end at n
            _SORT_SKIPPED["kway"].inc()
        else:
            note_fallback("kway_runs")
            _SORT_RAN.inc()
    local_ok = ts_enc.kind == "offset"
    lo = max(0, shift // spec.bucket_ms) if local_ok else 0
    use_width = width if local_ok else spec.num_buckets
    g = len(g_enc.dictionary)
    g_pad = max(8, 1 << (g - 1).bit_length())
    w_eff = min(use_width, spec.num_buckets - lo)
    key_slots = tuple(slot_of[nm] for nm in key_names)
    # group/ts positions INSIDE the sorted key outputs
    group_pos = key_names.index(spec.group_col)
    ts_pos = key_names.index(spec.ts_col)
    leaf_prog = tuple((slot_of[c], op) for c, op in prog)
    return DecodePlan(
        es=es, cap=cap, shift=shift, lo=lo, local_ok=local_ok,
        use_width=use_width, w_eff=w_eff, g=g, g_pad=g_pad,
        values=g_enc.dictionary, upload_names=upload_names,
        key_slots=key_slots, num_pks=len(pk_names),
        group_pos=group_pos, ts_pos=ts_pos,
        val_slot=slot_of[spec.value_col], leaf_prog=leaf_prog,
        consts=consts, route=route, run_offsets=run_offsets,
        num_runs=num_runs, which=spec.which,
        bucket_ms=spec.bucket_ms, num_buckets=spec.num_buckets)


def execute_plan(dp: DecodePlan) -> DecodeDispatch:
    """Upload one planned segment and issue its fused dispatch on the
    default device — the single-device tail of the old prepare path
    and the per-item fallback when a mesh round declines a plan."""
    es = dp.es
    t0 = time.perf_counter()
    upload_bytes = 0
    cols_dev = []
    for nm in dp.upload_names:
        arr = es.columns[nm]
        padded = np.zeros(dp.cap, dtype=arr.dtype)  # calloc: tail free
        padded[:es.n] = arr
        upload_bytes += int(padded.nbytes)
        cols_dev.append(deviceprof.device_put(padded))
    consts_dev = tuple(jnp.asarray(c) for c in dp.consts)
    offs_dev = jnp.int32(0) if dp.run_offsets is None \
        else jnp.asarray(dp.run_offsets)

    def run(pallas: bool):
        return _decode_aggregate_jit(
            tuple(cols_dev), es.n, consts_dev,
            np.int32(dp.shift), np.int32(dp.lo),
            np.int32(dp.num_buckets), np.int32(dp.bucket_ms), offs_dev,
            key_slots=dp.key_slots, num_pks=dp.num_pks,
            group_pos=dp.group_pos, ts_pos=dp.ts_pos,
            val_slot=dp.val_slot, leaf_prog=dp.leaf_prog,
            g_pad=dp.g_pad, width=dp.use_width, which=dp.which,
            use_pallas=pallas, route=dp.route, num_runs=dp.num_runs)

    if use_pallas_partials():
        try:
            outs, n_rows = run(True)
        except Exception as exc:  # noqa: BLE001 — guarded, classified
            reason = classify_pallas_failure()
            note_fallback(reason)
            logger.warning("pallas decode kernel failed (%s): %s; "
                           "using the XLA program", reason, exc)
            outs, n_rows = run(False)
    else:
        outs, n_rows = run(False)
    return DecodeDispatch(outs=outs, n_rows=n_rows,
                          values=dp.values, lo=dp.lo, w_eff=dp.w_eff,
                          bucket_ms=dp.bucket_ms,
                          t_dispatch=time.perf_counter() - t0,
                          upload_bytes=upload_bytes, src_rows=es.n)


def prepare_dispatch(es, spec, pk_names: list, seq_name: str,
                     leaves, max_bytes: int, width: int,
                     pad_capacity) -> "DecodeDispatch | DevicePart | str":
    """plan_dispatch + execute_plan in one step — the non-mesh entry
    point (and the shape every existing caller/test expects)."""
    dp = plan_dispatch(es, spec, pk_names, seq_name, leaves, max_bytes,
                       width, pad_capacity)
    if not isinstance(dp, DecodePlan):
        return dp
    return execute_plan(dp)
