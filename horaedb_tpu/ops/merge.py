"""Device merge-dedup: the north-star scan kernel.

Replaces the reference's streaming merge path — SortPreservingMergeExec
feeding MergeExec's row-at-a-time `primary_key_eq` scalar loop
(ref: src/storage/src/read.rs:154-156, 262-343) — with a single compiled
program over the concatenation of all SST batches in a segment:

  1. lexicographic sort by (pk..., seq)      — XLA variadic sort
  2. run-boundary mask (neighbor compare)    — vectorized, replaces the
                                               O(rows × pks) scalar loop
  3. segmented last-select per run           — LastValueOperator semantics
                                               (ref: operator.rs:37-44):
                                               equal PKs keep the row with
                                               the highest sequence

Everything is static-shape: inputs are padded to capacity with a validity
count; padding sorts to the end via an int32 sentinel.  Outputs are padded
too (first `num_runs` rows valid), so downstream ops stay compiled.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp

_PAD_SENTINEL = jnp.int32(2**31 - 1)

# Which merge strategy the scan uses (see storage/read.py):
#   host_perm   — exploit pre-sorted SST runs: the host plans a k-way
#                 merge permutation (or proves none is needed) and keeps
#                 the last row per PK run in one numpy pass
#                 (read._host_merge_window_descs); rows reach the device
#                 only as batched aggregation stacks.  The default.
#   device_sort — the original full `lax.sort` device program
#                 (`merge_dedup_last`); kept for A/B runs.
# `dedup_sorted_last` below is the DEVICE twin of the host dedup —
# exported for device-resident consumers and validated against
# merge_dedup_last in tests; the default scan path does not call it.
_MERGE_IMPLS = ("host_perm", "device_sort")
_merge_impl = "host_perm"


def set_merge_impl(name: str) -> None:
    global _merge_impl
    if name not in _MERGE_IMPLS:
        raise ValueError(f"unknown merge impl {name!r}; "
                         f"expected one of {_MERGE_IMPLS}")
    _merge_impl = name


def merge_impl() -> str:
    return _merge_impl


set_merge_impl(os.environ.get("HORAEDB_MERGE_IMPL", "host_perm"))


def sorted_run_starts(pk_cols: tuple, valid: jax.Array) -> jax.Array:
    """Boolean mask of primary-key run starts over sorted columns.

    This is the vectorized replacement for `primary_key_eq`
    (ref: read.rs:262-287): rows i and i-1 are in the same run iff all PK
    columns are equal.  Padding rows never start a run.
    """
    neq = jnp.zeros(valid.shape, dtype=bool)
    for col in pk_cols:
        neq = neq | (col != jnp.roll(col, 1))
    first = jnp.zeros_like(neq).at[0].set(True)
    return (first | neq) & valid


@functools.partial(jax.jit, static_argnames=("num_pks", "num_keys"))
def _merge_dedup_impl(cols: tuple, n_valid: jax.Array, num_pks: int, num_keys: int):
    capacity = cols[0].shape[0]
    iota = jnp.arange(capacity, dtype=jnp.int32)
    valid = iota < n_valid

    # Sort ONLY the key columns plus a row-index permutation; value columns
    # are fetched afterwards with a single fused gather.  With V value
    # columns this moves V arrays out of the O(n log n) sort and into an
    # O(n) gather.  Padding must sort last: pad keys become the int32 max
    # sentinel.  num_keys may be num_pks (seq known row-ordered: the
    # stable sort then keeps original order within a run, so last row per
    # run == highest seq without paying for seq as a sort operand).
    keys = tuple(jnp.where(valid, c, _PAD_SENTINEL) for c in cols[:num_keys])
    sorted_all = jax.lax.sort(keys + (iota,), num_keys=num_keys, is_stable=True)
    sorted_keys, perm = sorted_all[:-1], sorted_all[-1]
    sorted_valid = perm < n_valid

    run_starts = sorted_run_starts(sorted_keys[:num_pks], sorted_valid)
    run_ids = jnp.cumsum(run_starts.astype(jnp.int32)) - 1
    num_runs = jnp.sum(run_starts.astype(jnp.int32))

    # Last row of each run == highest seq for that PK (seq is the final
    # sort key).  segment_max over masked row indices finds it.
    masked_iota = jnp.where(sorted_valid, iota, jnp.int32(-1))
    safe_run_ids = jnp.where(sorted_valid, run_ids, capacity - 1)
    last_idx = jax.ops.segment_max(masked_iota, safe_run_ids, num_segments=capacity)
    gather_idx = jnp.clip(last_idx, 0, capacity - 1)

    # compose the two gathers: original row of the winning sorted position
    src_rows = perm[gather_idx]
    out_cols = tuple(c[src_rows] for c in cols)
    out_valid = iota < num_runs
    return out_cols, out_valid, num_runs


@functools.partial(jax.jit, static_argnames=("num_pks", "has_perm"))
def _dedup_presorted_impl(cols: tuple, perm, n_valid: jax.Array,
                          num_pks: int, has_perm: bool):
    capacity = cols[0].shape[0]
    iota = jnp.arange(capacity, dtype=jnp.int32)
    if has_perm:
        # one fused gather applies the host-computed merge permutation;
        # padding rows map to themselves (perm[n:] is identity)
        cols = tuple(c[perm] for c in cols)
    valid = iota < n_valid
    run_starts = sorted_run_starts(cols[:num_pks], valid)
    run_ids = jnp.cumsum(run_starts.astype(jnp.int32)) - 1
    num_runs = jnp.sum(run_starts.astype(jnp.int32))
    # within a run, the LAST row wins (rows arrive in seq-preference
    # order); segment_max over masked row indices finds it
    masked_iota = jnp.where(valid, iota, jnp.int32(-1))
    safe_run_ids = jnp.where(valid, run_ids, capacity - 1)
    last_idx = jax.ops.segment_max(masked_iota, safe_run_ids,
                                   num_segments=capacity)
    gather_idx = jnp.clip(last_idx, 0, capacity - 1)
    out_cols = tuple(c[gather_idx] for c in cols)
    out_valid = iota < num_runs
    return out_cols, out_valid, num_runs


def dedup_sorted_last(pk_cols: tuple, seq: jax.Array, value_cols: tuple,
                      n_valid, perm=None
                      ) -> tuple[tuple, jax.Array, tuple, jax.Array, jax.Array]:
    """Dedup WITHOUT a device sort: the k-way-merge replacement for
    `merge_dedup_last` when the caller already knows the row order.

    The reference merges already-sorted per-SST streams
    (SortPreservingMergeExec, ref: src/storage/src/read.rs:455-480)
    instead of re-sorting; our equivalent exploits that SSTs are written
    PK-sorted (storage.py write path): the host either verifies the
    concatenation is globally sorted (single-SST segments — the
    post-compaction steady state — and time-partitioned writes) or
    computes a merge permutation with an O(n) radix argsort over packed
    int64 keys, while the device only pays one fused gather plus the
    run-mask/segmented-last-select — the O(n log n) variadic
    `lax.sort` drops out of the scan entirely.

    Contract: after applying `perm` (or as given when `perm is None`),
    rows must be sorted by `pk_cols` lexicographically, with rows of
    equal PK ordered so the preferred (highest-seq) row comes LAST.

    Returns the same tuple shape as merge_dedup_last.
    """
    cols = tuple(pk_cols) + (seq,) + tuple(value_cols)
    has_perm = perm is not None
    if not has_perm:
        # jit requires consistent pytree arity; a scalar stands in
        perm = jnp.int32(0)
    out_cols, out_valid, num_runs = _dedup_presorted_impl(
        cols, perm, jnp.asarray(n_valid, dtype=jnp.int32),
        num_pks=len(pk_cols), has_perm=has_perm)
    out_pks = out_cols[: len(pk_cols)]
    out_seq = out_cols[len(pk_cols)]
    out_values = out_cols[len(pk_cols) + 1:]
    return out_pks, out_seq, out_values, out_valid, num_runs


def merge_dedup_last(pk_cols: tuple, seq: jax.Array, value_cols: tuple,
                     n_valid, seq_in_row_order: bool = False
                     ) -> tuple[tuple, tuple, jax.Array, jax.Array]:
    """Sort + dedup, keeping the last-by-sequence row per primary key.

    Args:
      pk_cols: int32 arrays (capacity,) — PK columns in schema order.
      seq: int32 array — per-row sequence rank (order-preserving).
      value_cols: arrays (capacity,) — carried value columns (any dtype).
      n_valid: scalar — number of real rows.
      seq_in_row_order: set True ONLY when seq is non-decreasing with
        row index (e.g. rows are concatenated SSTs sorted by file id and
        seq is the file id).  The stable PK sort then already places the
        highest-seq row last within each run, so seq is carried as a
        value column instead of paying for it as a sort operand.

    Returns (out_pk_cols, out_seq, out_value_cols, out_valid_mask, num_runs);
    outputs are sorted by PK ascending, padded to capacity.  out_seq carries
    each surviving row's original sequence — compaction rewrites depend on
    it for later cross-file dedup.
    """
    cols = tuple(pk_cols) + (seq,) + tuple(value_cols)
    out_cols, out_valid, num_runs = _merge_dedup_impl(
        cols, jnp.asarray(n_valid, dtype=jnp.int32),
        num_pks=len(pk_cols),
        num_keys=len(pk_cols) + (0 if seq_in_row_order else 1))
    out_pks = out_cols[: len(pk_cols)]
    out_seq = out_cols[len(pk_cols)]
    out_values = out_cols[len(pk_cols) + 1:]
    return out_pks, out_seq, out_values, out_valid, num_runs
