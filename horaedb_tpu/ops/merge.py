"""Device merge-dedup: the north-star scan kernel.

Replaces the reference's streaming merge path — SortPreservingMergeExec
feeding MergeExec's row-at-a-time `primary_key_eq` scalar loop
(ref: src/storage/src/read.rs:154-156, 262-343) — with a single compiled
program over the concatenation of all SST batches in a segment:

  1. lexicographic sort by (pk..., seq)      — XLA variadic sort
  2. run-boundary mask (neighbor compare)    — vectorized, replaces the
                                               O(rows × pks) scalar loop
  3. segmented last-select per run           — LastValueOperator semantics
                                               (ref: operator.rs:37-44):
                                               equal PKs keep the row with
                                               the highest sequence

Everything is static-shape: inputs are padded to capacity with a validity
count; padding sorts to the end via an int32 sentinel.  Outputs are padded
too (first `num_runs` rows valid), so downstream ops stay compiled.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp

from horaedb_tpu.common import deviceprof

_PAD_SENTINEL = jnp.int32(2**31 - 1)

# Which merge strategy the scan uses (see storage/read.py):
#   host_perm   — exploit pre-sorted SST runs: the host plans a k-way
#                 merge permutation (or proves none is needed) and keeps
#                 the last row per PK run in one numpy pass
#                 (read._host_merge_window_descs); rows reach the device
#                 only as batched aggregation stacks.  The default.
#   device_sort — the original full `lax.sort` device program
#                 (`merge_dedup_last`); kept for A/B runs.
# `dedup_sorted_last` below is the DEVICE twin of the host dedup —
# exported for device-resident consumers and validated against
# merge_dedup_last in tests; the default scan path does not call it.
_MERGE_IMPLS = ("host_perm", "device_sort")
_merge_impl = "host_perm"


def set_merge_impl(name: str) -> None:
    global _merge_impl
    if name not in _MERGE_IMPLS:
        raise ValueError(f"unknown merge impl {name!r}; "
                         f"expected one of {_MERGE_IMPLS}")
    _merge_impl = name


def merge_impl() -> str:
    return _merge_impl


set_merge_impl(os.environ.get("HORAEDB_MERGE_IMPL", "host_perm"))


def lex_sort(operands: tuple, num_keys: int,
             is_stable: bool = False) -> tuple:
    """THE `jax.lax.sort` seam: every variadic lexicographic device sort
    in the engine goes through here (tools/lint.py errors on `lax.sort`
    call sites outside this module), so the sort-vs-merge choice lives
    in one place and A/B instrumentation wraps one function."""
    return jax.lax.sort(tuple(operands), num_keys=num_keys,
                        is_stable=is_stable)


def _lex_less(ks: tuple, idx: jax.Array, xs: tuple):
    """Vectorized lexicographic compare of ks[:, idx] against xs[:, j]
    per slot j.  Returns (lt, eq) boolean arrays."""
    lt = jnp.zeros(idx.shape, dtype=bool)
    eq = jnp.ones(idx.shape, dtype=bool)
    for kcol, xcol in zip(ks, xs):
        probe = kcol[idx]
        lt = lt | (eq & (probe < xcol))
        eq = eq & (probe == xcol)
    return lt, eq


@deviceprof.jit(static_argnames=("num_runs",))
def _kway_merge_perm_impl(keys: tuple, offsets: jax.Array, num_runs: int):
    cap = keys[0].shape[0]
    iota = jnp.arange(cap, dtype=jnp.int32)
    # run of each original row; padded runs are empty so searchsorted
    # lands rows on the LAST run starting at-or-before them
    run_of = jnp.clip(
        jnp.searchsorted(offsets, iota, side="right").astype(jnp.int32) - 1,
        0, num_runs - 1)
    # within-run order IS sorted order (the caller's contract), and runs
    # are contiguous ascending — so the identity permutation is the
    # level-0 "sorted within every block" state
    perm = iota
    n_steps = max(1, cap - 1).bit_length() + 1
    level = 1
    while level < num_runs:
        ks = tuple(k[perm] for k in keys)  # keys in block-sorted order
        # elements never leave their block's row range, so the block of
        # slot j is the block of its element's original run
        blk = run_of[perm] // level
        p = blk >> 1
        base = 2 * level * p
        start = offsets[base]
        mid = offsets[base + level]
        end = offsets[base + 2 * level]
        in_a = iota < mid
        # merged rank of slot j's element within its pair block:
        #   A-side: own offset + |{b in B : key(b) <  key(j)}|
        #   B-side: own offset + |{a in A : key(a) <= key(j)}|
        # (runs are contiguous, so every B row index exceeds every A row
        # index — strict/leq encodes the original-row tiebreak exactly)
        lo = jnp.where(in_a, mid, start)
        hi = jnp.where(in_a, end, mid)
        for _ in range(n_steps):
            active = lo < hi
            probe = jnp.clip((lo + hi) // 2, 0, cap - 1)
            p_lt, p_eq = _lex_less(ks, probe, ks)
            go = active & jnp.where(in_a, p_lt, p_lt | p_eq)
            lo = jnp.where(go, probe + 1, lo)
            hi = jnp.where(go | ~active, hi, probe)
        new_slot = jnp.where(in_a,
                             iota + (lo - mid),
                             (iota - mid) + lo)
        perm = jnp.zeros(cap, dtype=jnp.int32).at[new_slot].set(perm)
        level *= 2
    return perm


def kway_merge_perm(keys: tuple, offsets, *, num_runs: int) -> jax.Array:
    """Permutation that stably merges `num_runs` presorted runs — the
    k-way replacement for the full variadic device sort when the store
    already delivers (pk, seq)-sorted per-SST runs.

    Args:
      keys: int32 arrays (capacity,), compare-priority order.  Rows of
        run r (indices [offsets[r], offsets[r+1])) must already be
        sorted lexicographically by `keys`, equal keys in row order.
      offsets: int32 (num_runs + 1,), non-decreasing, offsets[0] == 0,
        offsets[-1] == capacity.  Empty runs allowed — pad the run
        count to a power of two with empty runs to keep it static.
      num_runs: static run count (power of two).

    Returns perm (capacity,) int32 such that gathering rows by `perm`
    yields the stable sort by (keys..., original row index): a
    log2(num_runs)-level pairwise merge tree where each level ranks
    elements by their in-block position plus a lexicographic binary
    search over the partner block — O(n · log n · log k) compares
    instead of the sort's O(n · log² n) full key shuffles.
    """
    return _kway_merge_perm_impl(
        tuple(keys), jnp.asarray(offsets, dtype=jnp.int32),
        num_runs=num_runs)


def runs_lex_sorted_np(key_cols: list, offsets) -> bool:
    """Host-side admission check for `kway_merge_perm`: every run is
    individually lex-sorted by `key_cols` (numpy arrays).  O(n) per key
    column — the per-run twin of the whole-segment sortedness probe."""
    import numpy as np

    for a, b in zip(offsets[:-1], offsets[1:]):
        if b - a <= 1:
            continue
        later = np.zeros(b - a - 1, dtype=bool)
        for col in key_cols:
            seg = np.asarray(col[a:b])
            cur, nxt = seg[:-1], seg[1:]
            if ((cur > nxt) & ~later).any():
                return False
            later = later | (cur < nxt)
    return True


def sorted_run_starts(pk_cols: tuple, valid: jax.Array) -> jax.Array:
    """Boolean mask of primary-key run starts over sorted columns.

    This is the vectorized replacement for `primary_key_eq`
    (ref: read.rs:262-287): rows i and i-1 are in the same run iff all PK
    columns are equal.  Padding rows never start a run.
    """
    neq = jnp.zeros(valid.shape, dtype=bool)
    for col in pk_cols:
        neq = neq | (col != jnp.roll(col, 1))
    first = jnp.zeros_like(neq).at[0].set(True)
    return (first | neq) & valid


@deviceprof.jit(static_argnames=("num_pks", "num_keys"))
def _merge_dedup_impl(cols: tuple, n_valid: jax.Array, num_pks: int, num_keys: int):
    capacity = cols[0].shape[0]
    iota = jnp.arange(capacity, dtype=jnp.int32)
    valid = iota < n_valid

    # Sort ONLY the key columns plus a row-index permutation; value columns
    # are fetched afterwards with a single fused gather.  With V value
    # columns this moves V arrays out of the O(n log n) sort and into an
    # O(n) gather.  Padding must sort last: pad keys become the int32 max
    # sentinel.  num_keys may be num_pks (seq known row-ordered: the
    # stable sort then keeps original order within a run, so last row per
    # run == highest seq without paying for seq as a sort operand).
    keys = tuple(jnp.where(valid, c, _PAD_SENTINEL) for c in cols[:num_keys])
    sorted_all = jax.lax.sort(keys + (iota,), num_keys=num_keys, is_stable=True)
    sorted_keys, perm = sorted_all[:-1], sorted_all[-1]
    sorted_valid = perm < n_valid

    run_starts = sorted_run_starts(sorted_keys[:num_pks], sorted_valid)
    run_ids = jnp.cumsum(run_starts.astype(jnp.int32)) - 1
    num_runs = jnp.sum(run_starts.astype(jnp.int32))

    # Last row of each run == highest seq for that PK (seq is the final
    # sort key).  segment_max over masked row indices finds it.
    masked_iota = jnp.where(sorted_valid, iota, jnp.int32(-1))
    safe_run_ids = jnp.where(sorted_valid, run_ids, capacity - 1)
    last_idx = jax.ops.segment_max(masked_iota, safe_run_ids, num_segments=capacity)
    gather_idx = jnp.clip(last_idx, 0, capacity - 1)

    # compose the two gathers: original row of the winning sorted position
    src_rows = perm[gather_idx]
    out_cols = tuple(c[src_rows] for c in cols)
    out_valid = iota < num_runs
    return out_cols, out_valid, num_runs


@deviceprof.jit(static_argnames=("num_pks", "has_perm"))
def _dedup_presorted_impl(cols: tuple, perm, n_valid: jax.Array,
                          num_pks: int, has_perm: bool):
    capacity = cols[0].shape[0]
    iota = jnp.arange(capacity, dtype=jnp.int32)
    if has_perm:
        # one fused gather applies the host-computed merge permutation;
        # padding rows map to themselves (perm[n:] is identity)
        cols = tuple(c[perm] for c in cols)
    valid = iota < n_valid
    run_starts = sorted_run_starts(cols[:num_pks], valid)
    run_ids = jnp.cumsum(run_starts.astype(jnp.int32)) - 1
    num_runs = jnp.sum(run_starts.astype(jnp.int32))
    # within a run, the LAST row wins (rows arrive in seq-preference
    # order); segment_max over masked row indices finds it
    masked_iota = jnp.where(valid, iota, jnp.int32(-1))
    safe_run_ids = jnp.where(valid, run_ids, capacity - 1)
    last_idx = jax.ops.segment_max(masked_iota, safe_run_ids,
                                   num_segments=capacity)
    gather_idx = jnp.clip(last_idx, 0, capacity - 1)
    out_cols = tuple(c[gather_idx] for c in cols)
    out_valid = iota < num_runs
    return out_cols, out_valid, num_runs


def dedup_sorted_last(pk_cols: tuple, seq: jax.Array, value_cols: tuple,
                      n_valid, perm=None
                      ) -> tuple[tuple, jax.Array, tuple, jax.Array, jax.Array]:
    """Dedup WITHOUT a device sort: the k-way-merge replacement for
    `merge_dedup_last` when the caller already knows the row order.

    The reference merges already-sorted per-SST streams
    (SortPreservingMergeExec, ref: src/storage/src/read.rs:455-480)
    instead of re-sorting; our equivalent exploits that SSTs are written
    PK-sorted (storage.py write path): the host either verifies the
    concatenation is globally sorted (single-SST segments — the
    post-compaction steady state — and time-partitioned writes) or
    computes a merge permutation with an O(n) radix argsort over packed
    int64 keys, while the device only pays one fused gather plus the
    run-mask/segmented-last-select — the O(n log n) variadic
    `lax.sort` drops out of the scan entirely.

    Contract: after applying `perm` (or as given when `perm is None`),
    rows must be sorted by `pk_cols` lexicographically, with rows of
    equal PK ordered so the preferred (highest-seq) row comes LAST.

    Returns the same tuple shape as merge_dedup_last.
    """
    cols = tuple(pk_cols) + (seq,) + tuple(value_cols)
    has_perm = perm is not None
    if not has_perm:
        # jit requires consistent pytree arity; a scalar stands in
        perm = jnp.int32(0)
    out_cols, out_valid, num_runs = _dedup_presorted_impl(
        cols, perm, jnp.asarray(n_valid, dtype=jnp.int32),
        num_pks=len(pk_cols), has_perm=has_perm)
    out_pks = out_cols[: len(pk_cols)]
    out_seq = out_cols[len(pk_cols)]
    out_values = out_cols[len(pk_cols) + 1:]
    return out_pks, out_seq, out_values, out_valid, num_runs


def merge_dedup_last(pk_cols: tuple, seq: jax.Array, value_cols: tuple,
                     n_valid, seq_in_row_order: bool = False
                     ) -> tuple[tuple, tuple, jax.Array, jax.Array]:
    """Sort + dedup, keeping the last-by-sequence row per primary key.

    Args:
      pk_cols: int32 arrays (capacity,) — PK columns in schema order.
      seq: int32 array — per-row sequence rank (order-preserving).
      value_cols: arrays (capacity,) — carried value columns (any dtype).
      n_valid: scalar — number of real rows.
      seq_in_row_order: set True ONLY when seq is non-decreasing with
        row index (e.g. rows are concatenated SSTs sorted by file id and
        seq is the file id).  The stable PK sort then already places the
        highest-seq row last within each run, so seq is carried as a
        value column instead of paying for it as a sort operand.

    Returns (out_pk_cols, out_seq, out_value_cols, out_valid_mask, num_runs);
    outputs are sorted by PK ascending, padded to capacity.  out_seq carries
    each surviving row's original sequence — compaction rewrites depend on
    it for later cross-file dedup.
    """
    cols = tuple(pk_cols) + (seq,) + tuple(value_cols)
    out_cols, out_valid, num_runs = _merge_dedup_impl(
        cols, jnp.asarray(n_valid, dtype=jnp.int32),
        num_pks=len(pk_cols),
        num_keys=len(pk_cols) + (0 if seq_in_row_order else 1))
    out_pks = out_cols[: len(pk_cols)]
    out_seq = out_cols[len(pk_cols)]
    out_values = out_cols[len(pk_cols) + 1:]
    return out_pks, out_seq, out_values, out_valid, num_runs
