"""Top-k over per-group aggregates (BASELINE config #4: top-k hosts by
max(cpu) across 64 SSTs).

Runs on the dense (num_groups,) aggregate vector produced by
ops/downsample.py (or a psum-merged copy of it in the multi-chip path),
so k-selection is a single `lax.top_k` over group scores.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from horaedb_tpu.common import deviceprof


# ---------------------------------------------------------------------------
# exact double-float (hi/lo) accumulation — the additive-top-k score plane
# ---------------------------------------------------------------------------
#
# count/sum/avg rankings need the device score fold to reproduce the host
# control's f64 part-fold BIT-exactly, or the winner set can differ at the
# margin.  We borrow the rollup plane's compensated discipline: each cell's
# running sum is an unevaluated f32 pair (hi, lo) maintained with Knuth
# 2Sum, plus per-step flags that prove the pair still equals the exact sum.
# When every step is provably exact, the host's f64 fold over the same
# addends in the same order lands on the same value; any step that is NOT
# provably exact sets a sticky `lossy` flag and the round downgrades to the
# full-parts path (reason-counted, never silently wrong).


def two_sum(a: jax.Array, b: jax.Array):
    """Knuth 2Sum: s + e == a + b exactly (s = fl(a+b))."""
    s = a + b
    bv = s - a
    av = s - bv
    e = (a - av) + (b - bv)
    return s, e


def pair_add(hi: jax.Array, lo: jax.Array, x: jax.Array):
    """Add f32 `x` into the (hi, lo) pair.

    Returns (hi', lo', exact): `exact` is True when hi' + lo' provably
    equals the exact real sum hi + lo + x AND the pair stays "dense"
    enough that a host f64 fold of the same addends reproduces it.  The
    density guard (|lo'| tiny relative to hi', or zero) rejects pairs
    whose error term carries information beyond f64's 53-bit window —
    e.g. 1.0 + (2^-53 + 2^-77): the pair holds it exactly, an f64
    cannot, and equality with the host fold would break.  Over-flagging
    only costs a counted downgrade, never a wrong answer.
    """
    s, e = two_sum(hi, x)
    lo2, e1 = two_sum(lo, e)
    hi2, lo3 = two_sum(s, lo2)
    dense = (lo3 == 0.0) | (jnp.abs(lo3) * jnp.float32(2.0**28)
                            >= jnp.abs(hi2))
    exact = (e1 == 0.0) & dense & jnp.isfinite(hi2)
    return hi2, lo3, exact


def pair_max_normalized(hi: jax.Array, lo: jax.Array, mask: jax.Array,
                        axis: int, largest: bool = True):
    """Reduce (hi, lo) pairs along `axis` to the extreme REAL value.

    two_sum-maintained pairs are normalized (|lo| <= ulp(hi)/2), so the
    real-value order is the lexicographic (hi, lo) order: compare hi
    first, break ties on lo.  Masked-out cells never win; if nothing is
    masked in, the result is (-inf hi, 0 lo) [or +inf for smallest].
    Returns (hi_ext, lo_ext).
    """
    if not largest:
        h2, l2 = pair_max_normalized(-hi, -lo, mask, axis, largest=True)
        return -h2, -l2
    neg = jnp.float32(-jnp.inf)
    mh = jnp.where(mask, hi, neg)
    m_hi = jnp.max(mh, axis=axis, keepdims=True)
    at_max = mask & (mh == m_hi)
    m_lo = jnp.max(jnp.where(at_max, lo, neg), axis=axis,
                   keepdims=True)
    m_lo = jnp.where(jnp.isfinite(m_lo), m_lo, jnp.float32(0.0))
    return (jnp.squeeze(m_hi, axis=axis), jnp.squeeze(m_lo, axis=axis))


@deviceprof.jit(static_argnames=("k", "largest"))
def top_k_groups(scores: jax.Array, k: int, largest: bool = True):
    """Return (values, group_indices) of the top-k groups.

    `scores` is (num_groups,) float32; NaN scores (empty groups) always
    lose.  k must be static; if k > num_groups the tail is NaN/-1.
    """
    num_groups = scores.shape[0]
    neg_inf = jnp.float32(-jnp.inf)
    clean = jnp.where(jnp.isnan(scores), neg_inf if largest else -neg_inf, scores)
    work = clean if largest else -clean
    kk = min(k, num_groups)
    vals, idxs = jax.lax.top_k(work, kk)
    vals = vals if largest else -vals
    # groups that never matched anything are reported as invalid
    invalid = jnp.isinf(vals)
    vals = jnp.where(invalid, jnp.float32(jnp.nan), vals)
    idxs = jnp.where(invalid, -1, idxs)
    if kk < k:
        vals = jnp.concatenate([vals, jnp.full(k - kk, jnp.nan, dtype=vals.dtype)])
        idxs = jnp.concatenate([idxs, jnp.full(k - kk, -1, dtype=idxs.dtype)])
    return vals, idxs
