"""Top-k over per-group aggregates (BASELINE config #4: top-k hosts by
max(cpu) across 64 SSTs).

Runs on the dense (num_groups,) aggregate vector produced by
ops/downsample.py (or a psum-merged copy of it in the multi-chip path),
so k-selection is a single `lax.top_k` over group scores.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnames=("k", "largest"))
def top_k_groups(scores: jax.Array, k: int, largest: bool = True):
    """Return (values, group_indices) of the top-k groups.

    `scores` is (num_groups,) float32; NaN scores (empty groups) always
    lose.  k must be static; if k > num_groups the tail is NaN/-1.
    """
    num_groups = scores.shape[0]
    neg_inf = jnp.float32(-jnp.inf)
    clean = jnp.where(jnp.isnan(scores), neg_inf if largest else -neg_inf, scores)
    work = clean if largest else -clean
    kk = min(k, num_groups)
    vals, idxs = jax.lax.top_k(work, kk)
    vals = vals if largest else -vals
    # groups that never matched anything are reported as invalid
    invalid = jnp.isinf(vals)
    vals = jnp.where(invalid, jnp.float32(jnp.nan), vals)
    idxs = jnp.where(invalid, -1, idxs)
    if kk < k:
        vals = jnp.concatenate([vals, jnp.full(k - kk, jnp.nan, dtype=vals.dtype)])
        idxs = jnp.concatenate([idxs, jnp.full(k - kk, -1, dtype=idxs.dtype)])
    return vals, idxs
