"""JAX/XLA physical operators — the TPU compute core.

This layer replaces the reference's vendored DataFusion physical operators
(ParquetExec → FilterExec → SortPreservingMergeExec → MergeExec,
ref: src/storage/src/read.rs:429-494) with a TPU-first design:

- Columns live on device as int32 codes / float32 values only — no i64/u64
  on device.  Timestamps are int32 offsets from a per-query epoch; strings
  and u64 sequence numbers are order-preserving dictionary codes built on
  the host (ops/encode.py).  This keeps every array MXU/VPU-friendly and
  avoids x64 mode entirely.
- All ops are static-shape: batches are padded to capacity buckets and
  carry a row-validity count.  No recompilation per batch size.
- The CPU streaming k-way merge (SortPreservingMergeExec + MergeExec's
  row-at-a-time scalar loop, ref: read.rs:262-343) becomes ONE device-wide
  lexicographic sort over concatenated SST batches plus a vectorized
  run-boundary mask and segmented last-select (ops/merge.py).
- Time-bucket downsampling is a segmented reduction over
  (group, bucket) ids (ops/downsample.py).
"""

from horaedb_tpu.ops.encode import (
    ColumnEncoding,
    DeviceBatch,
    decode_to_arrow,
    encode_batch,
    pad_capacity,
)
from horaedb_tpu.ops.merge import (dedup_sorted_last, merge_dedup_last,
                                   merge_impl, set_merge_impl,
                                   sorted_run_starts)
from horaedb_tpu.ops.downsample import time_bucket_aggregate
from horaedb_tpu.ops.filter import (
    And,
    Eq,
    Ge,
    Gt,
    In,
    Le,
    Lt,
    Ne,
    Not,
    Or,
    TimeRangePred,
    eval_predicate,
)
from horaedb_tpu.ops.topk import top_k_groups

__all__ = [
    "And", "ColumnEncoding", "DeviceBatch", "Eq", "Ge", "Gt", "In", "Le",
    "Lt", "Ne", "Not", "Or", "TimeRangePred", "decode_to_arrow",
    "dedup_sorted_last", "encode_batch", "eval_predicate", "merge_dedup_last",
    "merge_impl", "set_merge_impl", "pad_capacity",
    "sorted_run_starts", "time_bucket_aggregate", "top_k_groups",
]
