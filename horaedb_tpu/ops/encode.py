"""Host-side encode/decode between Arrow RecordBatches and device batches.

The TPU wants fixed-width int32/float32 columns with static shapes; Arrow
delivers int64 timestamps, utf8 strings, and u64 sequences.  The bridge:

- string/binary  → order-preserving dictionary codes (Arrow C++
                   dictionary_encode, re-ranked to sorted order) + a host
                   dictionary for decode and predicate-constant lookup.
- int64 ts/seq   → int32 offset from a per-batch epoch (timestamps), or
                   order-preserving rank codes (sequences).  Ranks preserve
                   comparison order, which is all the merge needs.
- float64        → float32 (values; aggregation in f32, see downsample.py).
- rows           → padded to capacity buckets (next power of two, min 128)
                   so jit sees a small set of static shapes.

Decode inverts the mapping for result batches.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Optional

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from horaedb_tpu.common import deviceprof
from horaedb_tpu.common.error import Error, ensure
from horaedb_tpu.utils import registry

_INT32_MIN = np.int32(-(2**31))
_INT32_MAX = np.int32(2**31 - 1)

_F32_MAX = np.float32(np.finfo(np.float32).max)
# f64→f32 overflow policy: finite values beyond the f32 range CLAMP to
# ±f32::MAX and count here, instead of silently becoming inf and
# poisoning every aggregate over the segment.  Actual ±inf inputs pass
# through unchanged (the caller said inf, the cast didn't invent it).
_ENCODE_OVERFLOW = registry.counter(
    "horaedb_encode_overflow_total",
    "finite f64 values clamped to the f32 range during device encoding")

MIN_CAPACITY = 128


def pad_capacity(n: int) -> int:
    """Static-shape bucket for n rows: next power of two, >= MIN_CAPACITY."""
    cap = MIN_CAPACITY
    while cap < n:
        cap *= 2
    return cap


@dataclass(frozen=True)
class ColumnEncoding:
    """How one host column maps onto its device representation.

    kinds:
      numeric — value used as-is (int32/float32)
      offset  — host_value = epoch + device_value (timestamps: preserves
                arithmetic, so bucket = offset // width works on device)
      dict    — device value indexes `dictionary` (strings, and int64
                columns whose span exceeds int32 — e.g. __seq__, whose
                wall-clock-nanosecond values are near-constant-distinct
                per file but span far more than 2^31).  The dictionary is
                sorted, so codes are order-preserving — all compares and
                sorts need (strings via _dictionary_encode_arrow, int64
                via np.unique).
    """

    kind: str  # "numeric" | "dict" | "offset"
    arrow_type: pa.DataType
    dictionary: Optional[np.ndarray] = None  # kind == "dict"
    epoch: int = 0  # kind == "offset"


@dataclass
class DeviceBatch:
    """A padded, device-resident columnar batch.

    `columns` maps name → (capacity,)-shaped jax/numpy array (int32 or
    float32); rows [0, n_valid) are real, the rest padding.  `encodings`
    carries the host-side metadata needed to decode or to translate
    predicate constants.  `memo` holds derived per-batch artifacts (e.g.
    dense group mappings) so repeat queries over a cached batch skip
    recomputation; it is never part of the batch's identity.
    """

    columns: dict
    encodings: dict[str, ColumnEncoding]
    n_valid: int
    capacity: int
    memo: dict = field(default_factory=dict)
    # bytes currently held by `memo` values (maintained by the reader's
    # byte-bounded memo store; the scan cache charges an allowance for
    # this — see scan_cache.windows_nbytes)
    memo_bytes: int = 0

    @property
    def names(self) -> list[str]:
        return list(self.columns.keys())


def _offset_span_ok(np_col: np.ndarray) -> bool:
    if not len(np_col):
        return True
    # strictly below INT32_MAX: the merge kernel reserves the max value as
    # its padding sentinel (ops/merge.py)
    return int(np_col.max()) - int(np_col.min()) < int(_INT32_MAX)


def _encode_offset(np_col: np.ndarray) -> tuple[np.ndarray, int]:
    lo = int(np_col.min()) if len(np_col) else 0
    return (np_col - lo).astype(np.int32), lo


def _dictionary_encode(np_col: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    # Codes must be order-preserving (code comparison == value
    # comparison — load-bearing for the device sort producing the same
    # order as the reference's arrow sort), i.e. the dictionary is
    # sorted.  SST columns usually arrive already PK-sorted, where the
    # uniques are just the run starts — three O(n) passes instead of
    # np.unique's argsort.
    if len(np_col) and bool(np.all(np_col[:-1] <= np_col[1:])):
        new_run = np.empty(len(np_col), dtype=bool)
        new_run[0] = True
        np.not_equal(np_col[1:], np_col[:-1], out=new_run[1:])
        codes = np.cumsum(new_run, dtype=np.int64) - 1
        dictionary = np_col[new_run]
    else:
        dictionary, codes = np.unique(np_col, return_inverse=True)
    # strictly below INT32_MAX: the merge kernel reserves the max int32 as
    # its padding sentinel, so the largest code must never equal it
    ensure(len(dictionary) < int(_INT32_MAX), "dictionary overflow")
    return codes.astype(np.int32), dictionary


def _dictionary_encode_arrow(col: pa.Array) -> tuple[np.ndarray, np.ndarray]:
    """Order-preserving dictionary codes via Arrow's C++ kernels.

    pyarrow's dictionary_encode assigns codes by first appearance; we
    re-rank them by sorted dictionary order so code comparison == value
    comparison (same contract as _dictionary_encode) without touching
    per-row Python objects.
    """
    dict_arr = pc.dictionary_encode(col)
    if isinstance(dict_arr, pa.ChunkedArray):
        dict_arr = dict_arr.combine_chunks()
    codes = dict_arr.indices.to_numpy(zero_copy_only=False)
    dictionary = dict_arr.dictionary.to_numpy(zero_copy_only=False)
    # see _dictionary_encode: max code must stay below the pad sentinel
    ensure(len(dictionary) < int(_INT32_MAX), "dictionary overflow")
    order = np.argsort(dictionary)  # sorts only the uniques
    rank = np.empty(len(order), dtype=np.int32)
    rank[order] = np.arange(len(order), dtype=np.int32)
    return rank[codes], dictionary[order]


def encode_column(col: pa.Array, name: str) -> tuple[np.ndarray, ColumnEncoding]:
    t = col.type
    if pa.types.is_floating(t):
        host = col.to_numpy(zero_copy_only=False)
        with np.errstate(over="ignore"):  # overflow handled below
            out = host.astype(np.float32)
        if host.dtype == np.float64:
            overflow = np.isinf(out) & np.isfinite(host)
            n = int(np.count_nonzero(overflow))
            if n:
                _ENCODE_OVERFLOW.inc(n)
                np.copyto(out, np.sign(host).astype(np.float32) * _F32_MAX,
                          where=overflow)
        return out, ColumnEncoding("numeric", t)
    if pa.types.is_integer(t):
        np_col = col.to_numpy(zero_copy_only=False)
        if np_col.dtype in (np.int8, np.int16, np.int32, np.uint8, np.uint16):
            return np_col.astype(np.int32), ColumnEncoding("numeric", t)
        # int64/uint64/uint32: shift to an epoch when the span fits int32
        # (timestamps — keeps device arithmetic), else rank-encode through
        # a sorted-unique dictionary (sequences — exact and ordered).
        ensure(len(np_col) == 0 or int(np_col.max()) <= 2**63 - 1,
               "u64 values beyond i64::MAX are not supported on device")
        np64 = np_col.astype(np.int64)
        if _offset_span_ok(np64):
            dev, epoch = _encode_offset(np64)
            return dev, ColumnEncoding("offset", t, epoch=epoch)
        codes, dictionary = _dictionary_encode(np64)
        return codes, ColumnEncoding("dict", t, dictionary=dictionary)
    if pa.types.is_string(t) or pa.types.is_large_string(t) or pa.types.is_binary(t):
        codes, dictionary = _dictionary_encode_arrow(col)
        return codes, ColumnEncoding("dict", t, dictionary=dictionary)
    raise Error(f"unsupported column type for device encoding: {name}: {t}")


def encode_batch(batch: pa.RecordBatch, capacity: Optional[int] = None,
                 device_put=None) -> DeviceBatch:
    """Encode an Arrow batch into a padded DeviceBatch.

    `device_put` (e.g. jax.device_put or a sharding-aware variant) is
    applied to each padded column; defaults to leaving numpy arrays for
    the caller to transfer.
    """
    n = batch.num_rows
    cap = capacity if capacity is not None else pad_capacity(n)
    ensure(cap >= n, f"capacity {cap} < rows {n}")
    columns = {}
    encodings = {}
    for name, col in zip(batch.schema.names, batch.columns):
        # No silent null-fill: a null turned into 0.0 would corrupt
        # min/count/avg downstream.  Null masks are not carried on device
        # yet, so reject at the boundary.
        ensure(col.null_count == 0,
               f"column {name!r} contains nulls; device encoding carries no "
               "null mask — drop or fill nulls before writing")
        dev, enc = encode_column(col, name)
        padded = np.zeros(cap, dtype=dev.dtype)
        padded[:n] = dev
        if device_put is None:
            columns[name] = padded
        else:
            t0 = time.perf_counter()
            columns[name] = device_put(padded)
            # profiler-owned puts charge themselves — don't double-count
            if getattr(device_put, "__self__", None) \
                    is not deviceprof.profiler:
                deviceprof.charge_transfer(
                    "h2d", int(padded.nbytes), time.perf_counter() - t0)
        encodings[name] = enc
    return DeviceBatch(columns=columns, encodings=encodings, n_valid=n, capacity=cap)


def decode_column(dev_col: np.ndarray, enc: ColumnEncoding, n_valid: int) -> pa.Array:
    host = np.asarray(dev_col)[:n_valid]
    if enc.kind == "numeric":
        return pa.array(host, type=enc.arrow_type).cast(enc.arrow_type)
    if enc.kind == "offset":
        return pa.array(host.astype(np.int64) + enc.epoch, type=enc.arrow_type)
    if enc.kind == "dict":
        if enc.dictionary.dtype == object:
            # string/binary: build a DictionaryArray (one C++ gather) and
            # cast instead of materializing Python objects per row
            dict_values = pa.array(enc.dictionary, type=enc.arrow_type)
            darr = pa.DictionaryArray.from_arrays(
                pa.array(host, type=pa.int32()), dict_values)
            return darr.cast(enc.arrow_type)
        return pa.array(enc.dictionary[host], type=enc.arrow_type)
    raise Error(f"unknown encoding kind: {enc.kind}")


def decode_to_arrow(batch: DeviceBatch, schema: Optional[pa.Schema] = None,
                    names: Optional[list[str]] = None) -> pa.RecordBatch:
    names = names if names is not None else batch.names
    arrays = [decode_column(batch.columns[n], batch.encodings[n], batch.n_valid)
              for n in names]
    if schema is not None:
        return pa.RecordBatch.from_arrays(arrays, schema=schema)
    return pa.RecordBatch.from_arrays(arrays, names=names)
