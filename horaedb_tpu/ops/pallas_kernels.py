"""Pallas TPU kernel: fused time-bucket segmented aggregation.

The XLA path (ops/downsample.py) lowers jax.ops.segment_* to sort/scatter
programs.  On TPU, scatters serialize; this kernel instead computes the
(group, bucket) reduction as compare-broadcast tiles — the standard
Pallas pattern for segmented reductions over SMALL dense grids, which is
exactly the downsample shape (cells = groups x buckets, typically <= a
few thousand):

  grid = (cell_tiles, row_blocks)        # rows innermost
  per step: load a (1, BLOCK_ROWS) slab of rows, build the
  (CELL_TILE, BLOCK_ROWS) membership mask `cell_id == tile_cells`,
  and accumulate sum/count/min/max along the row axis into VMEM-resident
  (1, CELL_TILE) output blocks that persist across the row-block loop
  (output revisiting: the out index_map ignores the row index).

No data-dependent shapes, no scatter, one pass over the rows per cell
tile.  Cost is O(rows x cells / tile-parallelism): the right trade for
small grids, measured against the XLA path by bench before adoption
(the XLA path stays the default).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_F32_MAX = float(jnp.finfo(jnp.float32).max)

BLOCK_ROWS = 1024
CELL_TILE = 512


def _agg_kernel(meta_ref, ts_ref, gid_ref, val_ref,
                sum_ref, cnt_ref, min_ref, max_ref, *,
                num_groups: int, num_buckets: int, cell_tile: int):
    ri = pl.program_id(1)
    ci = pl.program_id(0)
    n_valid = meta_ref[0]
    bucket_ms = meta_ref[1]

    @pl.when(ri == 0)
    def _init():
        sum_ref[...] = jnp.zeros_like(sum_ref)
        cnt_ref[...] = jnp.zeros_like(cnt_ref)
        min_ref[...] = jnp.full_like(min_ref, _F32_MAX)
        max_ref[...] = jnp.full_like(max_ref, -_F32_MAX)

    block_rows = ts_ref.shape[1]
    ts = ts_ref[0, :]
    gid = gid_ref[0, :]
    val = val_ref[0, :]

    row0 = ri * block_rows
    row_ids = row0 + jax.lax.broadcasted_iota(jnp.int32, (1, block_rows), 1)[0]
    bucket = ts // bucket_ms
    # full XLA-path guard incl. gid upper bound: without it an oversized
    # gid could overflow `cell` and wrap into a valid tile
    in_grid = (row_ids < n_valid) & (bucket >= 0) & (bucket < num_buckets) \
        & (gid >= 0) & (gid < num_groups)
    cell = jnp.where(in_grid, gid * num_buckets + bucket, jnp.int32(-1))

    base = ci * cell_tile
    tile_cells = base + jax.lax.broadcasted_iota(
        jnp.int32, (cell_tile, block_rows), 0)
    member = (cell[None, :] == tile_cells) & in_grid[None, :]

    vals2d = jnp.broadcast_to(val[None, :], (cell_tile, block_rows))
    sum_ref[0, :] += jnp.sum(jnp.where(member, vals2d, 0.0), axis=1)
    cnt_ref[0, :] += jnp.sum(member.astype(jnp.float32), axis=1)
    min_ref[0, :] = jnp.minimum(
        min_ref[0, :], jnp.min(jnp.where(member, vals2d, _F32_MAX), axis=1))
    max_ref[0, :] = jnp.maximum(
        max_ref[0, :], jnp.max(jnp.where(member, vals2d, -_F32_MAX), axis=1))


@functools.partial(jax.jit, static_argnames=("num_groups", "num_buckets",
                                             "interpret"))
def pallas_time_bucket_aggregate(ts_offset: jax.Array, group_ids: jax.Array,
                                 values: jax.Array, n_valid, bucket_ms,
                                 num_groups: int, num_buckets: int,
                                 interpret: bool = False) -> dict:
    """Pallas twin of ops.downsample.time_bucket_aggregate (sum/count/
    min/max/avg; no `last`).  Same contract: int32 ts offsets and group
    codes, capacity-padded, rows [0, n_valid) real."""
    capacity = ts_offset.shape[0]
    num_cells = num_groups * num_buckets
    cells_padded = pl.cdiv(num_cells, CELL_TILE) * CELL_TILE
    rows_padded = pl.cdiv(capacity, BLOCK_ROWS) * BLOCK_ROWS

    pad_rows = rows_padded - capacity
    ts2 = jnp.pad(ts_offset, (0, pad_rows)).reshape(1, rows_padded)
    gid2 = jnp.pad(group_ids, (0, pad_rows), constant_values=-1) \
        .reshape(1, rows_padded)
    val2 = jnp.pad(values, (0, pad_rows)).reshape(1, rows_padded)
    meta = jnp.asarray([n_valid, bucket_ms], dtype=jnp.int32)

    grid = (cells_padded // CELL_TILE, rows_padded // BLOCK_ROWS)
    row_spec = pl.BlockSpec((1, BLOCK_ROWS), lambda ci, ri: (0, ri))
    out_spec = pl.BlockSpec((1, CELL_TILE), lambda ci, ri: (0, ci))
    out_shape = jax.ShapeDtypeStruct((1, cells_padded), jnp.float32)

    kernel = functools.partial(_agg_kernel, num_groups=num_groups,
                               num_buckets=num_buckets, cell_tile=CELL_TILE)
    meta_spec = pl.BlockSpec((2,), lambda ci, ri: (0,),
                             memory_space=pltpu.SMEM)
    sums, counts, mins, maxs = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[meta_spec, row_spec, row_spec, row_spec],
        out_specs=[out_spec] * 4,
        out_shape=[out_shape] * 4,
        interpret=interpret,
    )(meta, ts2, gid2, val2)

    grid_of = lambda a: a[0, :num_cells].reshape(num_groups, num_buckets)
    count = grid_of(counts)
    empty = count == 0
    nan = jnp.float32(jnp.nan)
    total = grid_of(sums)
    inf = jnp.float32(jnp.inf)
    # empty-cell identities match the XLA path (+inf/-inf, not +/-F32_MAX)
    return {
        "count": count,
        "sum": total,
        "min": jnp.where(empty, inf, grid_of(mins)),
        "max": jnp.where(empty, -inf, grid_of(maxs)),
        "avg": jnp.where(empty, nan, total / jnp.maximum(count, 1.0)),
    }
