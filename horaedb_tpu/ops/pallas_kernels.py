"""Pallas TPU kernel: fused time-bucket segmented aggregation.

The XLA path (ops/downsample.py) lowers jax.ops.segment_* to sort/scatter
programs.  On TPU, scatters serialize; this kernel instead computes the
(group, bucket) reduction as compare-broadcast tiles — the standard
Pallas pattern for segmented reductions over SMALL dense grids, which is
exactly the downsample shape (cells = groups x buckets, typically <= a
few thousand):

  grid = (cell_tiles, row_blocks)        # rows innermost
  per step: load a (1, BLOCK_ROWS) slab of rows, build the
  (CELL_TILE, BLOCK_ROWS) membership mask `cell_id == tile_cells`,
  and accumulate sum/count/min/max along the row axis into VMEM-resident
  (1, CELL_TILE) output blocks that persist across the row-block loop
  (output revisiting: the out index_map ignores the row index).

No data-dependent shapes, no scatter, one pass over the rows per cell
tile.  Cost is O(rows x cells / tile-parallelism): the right trade for
small grids, measured against the XLA path by bench before adoption
(the XLA path stays the default).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from horaedb_tpu.common import deviceprof

_F32_MAX = float(jnp.finfo(jnp.float32).max)

BLOCK_ROWS = 1024
CELL_TILE = 512


_I32_MIN = -(2**31)
# output field order; a `fields` subset (static per compile) selects
# which accumulators exist at all
_FIELDS = ("count", "sum", "min", "max", "last_ts", "last")
_INIT = {"count": 0.0, "sum": 0.0, "min": _F32_MAX, "max": -_F32_MAX,
         "last_ts": _I32_MIN, "last": 0.0}


def _agg_kernel(meta_ref, ts_ref, gid_ref, val_ref, *out_refs,
                num_groups: int, num_buckets: int, cell_tile: int,
                fields: tuple):
    refs = dict(zip(fields, out_refs))
    ri = pl.program_id(1)
    ci = pl.program_id(0)
    n_valid = meta_ref[0]
    bucket_ms = meta_ref[1]

    @pl.when(ri == 0)
    def _init():
        for name, ref in refs.items():
            ref[...] = jnp.full_like(ref, _INIT[name])

    block_rows = ts_ref.shape[1]
    ts = ts_ref[0, :]
    gid = gid_ref[0, :]
    val = val_ref[0, :]

    row0 = ri * block_rows
    row_ids = row0 + jax.lax.broadcasted_iota(jnp.int32, (1, block_rows), 1)[0]
    bucket = ts // bucket_ms
    # full XLA-path guard incl. gid upper bound: without it an oversized
    # gid could overflow `cell` and wrap into a valid tile
    in_grid = (row_ids < n_valid) & (bucket >= 0) & (bucket < num_buckets) \
        & (gid >= 0) & (gid < num_groups)
    cell = jnp.where(in_grid, gid * num_buckets + bucket, jnp.int32(-1))

    base = ci * cell_tile
    tile_cells = base + jax.lax.broadcasted_iota(
        jnp.int32, (cell_tile, block_rows), 0)
    member = (cell[None, :] == tile_cells) & in_grid[None, :]

    vals2d = jnp.broadcast_to(val[None, :], (cell_tile, block_rows))
    refs["count"][0, :] += jnp.sum(member.astype(jnp.float32), axis=1)
    if "sum" in refs:
        refs["sum"][0, :] += jnp.sum(jnp.where(member, vals2d, 0.0), axis=1)
    if "min" in refs:
        refs["min"][0, :] = jnp.minimum(
            refs["min"][0, :],
            jnp.min(jnp.where(member, vals2d, _F32_MAX), axis=1))
    if "max" in refs:
        refs["max"][0, :] = jnp.maximum(
            refs["max"][0, :],
            jnp.max(jnp.where(member, vals2d, -_F32_MAX), axis=1))
    if "last" in refs:
        # `last` = value at the max ts per cell, later row winning ties.
        # Within the block: pick the member row with max (ts, row) — row
        # ids are distinct, so a one-hot on max row-at-max-ts is exact.
        ts2d = jnp.where(member, jnp.broadcast_to(ts[None, :],
                                                  (cell_tile, block_rows)),
                         _I32_MIN)
        blk_ts = jnp.max(ts2d, axis=1)
        at_max = member & (ts2d == blk_ts[:, None])
        rows2d = jnp.broadcast_to(row_ids[None, :], (cell_tile, block_rows))
        rank = jnp.where(at_max, rows2d, -1)
        best = jnp.max(rank, axis=1)
        one_hot = at_max & (rank == best[:, None])
        blk_val = jnp.sum(jnp.where(one_hot, vals2d, 0.0), axis=1)
        blk_has = jnp.any(member, axis=1)
        # rows arrive in increasing row order across blocks, so a later
        # block with an equal max ts must win — mirror the XLA tie-break
        take = blk_has & (blk_ts >= refs["last_ts"][0, :])
        refs["last_ts"][0, :] = jnp.where(take, blk_ts,
                                          refs["last_ts"][0, :])
        refs["last"][0, :] = jnp.where(take, blk_val, refs["last"][0, :])


def _fields_for(which: tuple) -> tuple:
    """Accumulator fields for a canonical `which` tuple, dependencies
    included (avg needs sum, last needs last_ts, count always)."""
    want = set(which)
    if "avg" in want:
        want.add("sum")
    if "last" in want:
        want.add("last_ts")
    want.add("count")
    return tuple(f for f in _FIELDS if f in want)


def _pallas_partial_grids(ts_offset: jax.Array, group_ids: jax.Array,
                          values: jax.Array, n_valid, bucket_ms,
                          num_groups: int, num_buckets: int,
                          fields: tuple, interpret: bool) -> dict:
    """Run the compare-broadcast kernel and reshape its flat cell
    outputs into (num_groups, num_buckets) PARTIAL grids with the
    segment-op identities the XLA path produces (min/max empties read
    +/-inf, last_ts I32_MIN, last 0) — the shape combine folds and
    finalize_aggregate consumes."""
    capacity = ts_offset.shape[0]
    num_cells = num_groups * num_buckets
    cells_padded = pl.cdiv(num_cells, CELL_TILE) * CELL_TILE
    rows_padded = pl.cdiv(capacity, BLOCK_ROWS) * BLOCK_ROWS

    pad_rows = rows_padded - capacity
    ts2 = jnp.pad(ts_offset, (0, pad_rows)).reshape(1, rows_padded)
    gid2 = jnp.pad(group_ids, (0, pad_rows), constant_values=-1) \
        .reshape(1, rows_padded)
    val2 = jnp.pad(values, (0, pad_rows)).reshape(1, rows_padded)
    meta = jnp.asarray([n_valid, bucket_ms], dtype=jnp.int32)

    grid = (cells_padded // CELL_TILE, rows_padded // BLOCK_ROWS)
    row_spec = pl.BlockSpec((1, BLOCK_ROWS), lambda ci, ri: (0, ri))
    out_spec = pl.BlockSpec((1, CELL_TILE), lambda ci, ri: (0, ci))
    out_f32 = jax.ShapeDtypeStruct((1, cells_padded), jnp.float32)
    out_i32 = jax.ShapeDtypeStruct((1, cells_padded), jnp.int32)

    kernel = functools.partial(_agg_kernel, num_groups=num_groups,
                               num_buckets=num_buckets,
                               cell_tile=CELL_TILE, fields=fields)
    meta_spec = pl.BlockSpec((2,), lambda ci, ri: (0,),
                             memory_space=pltpu.SMEM)
    outs = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[meta_spec, row_spec, row_spec, row_spec],
        out_specs=[out_spec] * len(fields),
        out_shape=[out_i32 if f == "last_ts" else out_f32
                   for f in fields],
        interpret=interpret,
    )(meta, ts2, gid2, val2)

    grid_of = lambda a: a[0, :num_cells].reshape(num_groups, num_buckets)
    # shaped exactly like a combined XLA partial so finalize_aggregate
    # is the single emission rule; empty-cell min/max convert from the
    # kernel's +/-F32_MAX accumulator identity to the segment-op
    # identity (+/-inf) the XLA path produces
    partial = {f: grid_of(a) for f, a in zip(fields, outs)}
    empty = partial["count"] == 0
    if "min" in partial:
        partial["min"] = jnp.where(empty, jnp.inf, partial["min"])
    if "max" in partial:
        partial["max"] = jnp.where(empty, -jnp.inf, partial["max"])
    return partial


@deviceprof.jit(static_argnames=("num_groups", "num_buckets",
                                "which", "interpret"))
def pallas_time_bucket_aggregate(ts_offset: jax.Array, group_ids: jax.Array,
                                 values: jax.Array, n_valid, bucket_ms,
                                 num_groups: int, num_buckets: int,
                                 which: tuple = None,
                                 interpret: bool = False) -> dict:
    """Pallas twin of ops.downsample.time_bucket_aggregate, including
    `last` (value at max ts per cell, later row winning ties).  Same
    contract: int32 ts offsets and group codes, capacity-padded, rows
    [0, n_valid) real.  `which` (static) limits the accumulators the
    kernel materializes — cost scales with the requested aggregates,
    like the XLA path."""
    from horaedb_tpu.ops import downsample

    which = tuple(sorted(set(which))) if which is not None \
        else downsample.ALL_AGGS
    partial = _pallas_partial_grids(
        ts_offset, group_ids, values, n_valid, bucket_ms,
        num_groups=num_groups, num_buckets=num_buckets,
        fields=_fields_for(which), interpret=interpret)
    return downsample.finalize_aggregate(partial, which=which)


def pallas_window_partials(ts_offset: jax.Array, group_ids: jax.Array,
                           values: jax.Array, n_valid, bucket_ms,
                           num_groups: int, num_buckets: int,
                           which: tuple, interpret: bool = False) -> dict:
    """PARTIAL-grid twin of pallas_time_bucket_aggregate for the fused
    device-decode dispatch (ops/device_decode.py): same kernel, no
    finalize — the emitted grids carry the partial conventions
    (min/max empties +/-inf, last_ts I32_MIN) that the host combine
    fold (storage/combine.py) consumes directly.  Callers pre-mask
    out-of-range rows to gid = -1 (the decode program's filter/dedup
    masks), matching ops.downsample.window_local_partials.  Traced:
    meant to be called INSIDE an enclosing jit (the fused dispatch),
    so it carries no jit wrapper of its own."""
    return _pallas_partial_grids(
        ts_offset, group_ids, values, n_valid, bucket_ms,
        num_groups=num_groups, num_buckets=num_buckets,
        fields=_fields_for(tuple(sorted(set(which)))),
        interpret=interpret)
