"""Time-bucketed downsampling as segmented reductions.

The reference has no downsample operator yet (the legacy engine pushes
sum/rate into DataFusion aggregates; RFC 20220702 splits them to a query
frontend).  Here it is a first-class device op because it IS the north-star
workload (BASELINE.md configs 1-3, 5): `GROUP BY series, time(bucket)`
over min/max/sum/count/avg/last.

Shape discipline: output is a dense (num_groups, num_buckets) grid —
group ids are dictionary codes, bucket ids are (ts - range_start) //
bucket_ms.  Both counts are static per query, so jit compiles one program
per (capacity, groups, buckets) signature.

Split into partial_aggregate / finalize_aggregate so the multi-chip path
(parallel/scan.py) can psum/pmax partial grids across the segment mesh
axis before finalizing — the identity elements (0, +/-inf, INT32_MIN)
combine correctly under collectives, NaNs would not.
"""

from __future__ import annotations

import logging
import os

import jax
import jax.numpy as jnp

from horaedb_tpu.common import deviceprof

_F32_MAX = jnp.float32(jnp.finfo(jnp.float32).max)
_I32_MIN = jnp.int32(-(2**31))


ALL_AGGS = ("count", "sum", "min", "max", "avg", "last")


def partial_aggregate(ts_offset: jax.Array, group_ids: jax.Array,
                      values: jax.Array, n_valid, bucket_ms,
                      num_groups: int, num_buckets: int,
                      which: tuple = ALL_AGGS) -> dict:
    """Raw per-shard aggregate grids, all (num_groups, num_buckets):

      sum (0-init), count (0), min (+F32_MAX), max (-F32_MAX),
      last_ts (I32_MIN), last (0 where empty).

    `which` restricts computation to the requested aggregates (plus
    their dependencies: avg needs sum+count, last needs last_ts; count
    is always produced — finalize and cross-shard combining key on it).
    Combinable across shards: sum/count by +, min by min, max by max,
    (last_ts, last) by argmax-ts with later-shard tie-break.
    """
    want = set(which)
    unknown = want - set(ALL_AGGS)
    if unknown:
        raise ValueError(f"unknown aggregates {sorted(unknown)}; "
                         f"supported: {ALL_AGGS}")
    if "avg" in want:
        want.add("sum")
    capacity = ts_offset.shape[0]
    iota = jnp.arange(capacity, dtype=jnp.int32)
    valid = iota < jnp.asarray(n_valid, dtype=jnp.int32)

    bucket = ts_offset // jnp.asarray(bucket_ms, dtype=jnp.int32)
    in_grid = valid & (bucket >= 0) & (bucket < num_buckets) \
        & (group_ids >= 0) & (group_ids < num_groups)
    num_cells = num_groups * num_buckets
    # out-of-grid rows land in an overflow cell that is sliced away
    seg = jnp.where(in_grid, group_ids * num_buckets + bucket, num_cells)

    grid = lambda a: a.reshape(num_groups, num_buckets)
    ones = in_grid.astype(jnp.float32)
    out = {"count": grid(jax.ops.segment_sum(
        ones, seg, num_segments=num_cells + 1)[:num_cells])}
    if "sum" in want:
        out["sum"] = grid(jax.ops.segment_sum(
            jnp.where(in_grid, values, 0.0), seg,
            num_segments=num_cells + 1)[:num_cells])
    if "min" in want:
        out["min"] = grid(jax.ops.segment_min(
            jnp.where(in_grid, values, _F32_MAX), seg,
            num_segments=num_cells + 1)[:num_cells])
    if "max" in want:
        out["max"] = grid(jax.ops.segment_max(
            jnp.where(in_grid, values, -_F32_MAX), seg,
            num_segments=num_cells + 1)[:num_cells])
    if "last" in want:
        # "last" = value at the highest timestamp in the cell (later row
        # wins ties, mirroring last-value merge semantics).  Two segmented
        # passes: max ts per cell, then max row index at that ts.
        tmax = jax.ops.segment_max(
            jnp.where(in_grid, ts_offset, _I32_MIN), seg,
            num_segments=num_cells + 1)
        at_max_ts = in_grid & (ts_offset == tmax[seg])
        last_row = jax.ops.segment_max(
            jnp.where(at_max_ts, iota, -1), seg,
            num_segments=num_cells + 1)[:num_cells]
        out["last"] = grid(jnp.where(
            last_row >= 0, values[jnp.clip(last_row, 0, capacity - 1)], 0.0))
        out["last_ts"] = grid(tmax[:num_cells])
    return out


def window_local_partials(ts, gid_local, vals, remap, shift, lo,
                          total_buckets, bucket_ms, *, num_groups: int,
                          num_buckets: int, which: tuple = ALL_AGGS) -> dict:
    """One window's partial grids over its LOCAL bucket range — the
    shared inner of the engine's batched (vmap) and meshed (shard_map)
    aggregation programs.

    Args:
      ts: int32 (capacity,) — encoded ts (offsets from the window's
        epoch).
      gid_local: int32 (capacity,) — window-local dense group codes;
        -1 = dropped row (padding or predicate-filtered).
      remap: int32 (num_groups,) — local code -> union-group row.
      shift: scalar int32 — ts + shift = offset from the query range
        start.
      lo: scalar int32 — first bucket this window's grid covers; local
        grid bucket b corresponds to global bucket lo + b.
      total_buckets: traced scalar — global bucket count; rows at or
        beyond it are dropped (windows may overhang the query range).
      num_buckets: static LOCAL grid width.
    """
    gid_union = jnp.where(
        gid_local >= 0,
        remap[jnp.clip(gid_local, 0, remap.shape[0] - 1)], -1)
    bucket_ms = jnp.asarray(bucket_ms, jnp.int32)
    ts_global = ts + jnp.asarray(shift, jnp.int32)
    bucket_global = ts_global // bucket_ms
    gid_union = jnp.where(
        bucket_global < jnp.asarray(total_buckets, jnp.int32),
        gid_union, -1)
    # exact: (a - lo*b) // b == a//b - lo for integer floor division
    ts_local = ts_global - jnp.asarray(lo, jnp.int32) * bucket_ms
    return partial_aggregate(ts_local, gid_union, vals, ts.shape[0],
                             bucket_ms, num_groups=num_groups,
                             num_buckets=num_buckets, which=which)


def combine_partial_pair(cur: dict, prev: dict) -> dict:
    """Pairwise combine of two partial-grid dicts over the SAME local
    bucket span — the associative op of the mesh scan's segmented time
    -axis reduction (parallel/scan.py mesh_run_partials).  `prev` is
    the EARLIER prefix; ties on last_ts keep `cur` (later window wins,
    mirroring the host fold's `>=` take in storage/combine.py).

    Exactness: count adds are exact integer-valued f32 while a cell's
    combined count stays < 2^24 (the dispatcher bounds time_axis x
    capacity); min/max/last are selection ops; sum is exact only for
    cells with a single contributing window — the dispatcher's overlap
    gate keeps multi-contributor sums off the mesh."""
    out = {"count": cur["count"] + prev["count"]}
    if "sum" in cur:
        out["sum"] = cur["sum"] + prev["sum"]
    if "min" in cur:
        out["min"] = jnp.minimum(cur["min"], prev["min"])
    if "max" in cur:
        out["max"] = jnp.maximum(cur["max"], prev["max"])
    if "last" in cur:
        take_cur = cur["last_ts"] >= prev["last_ts"]
        out["last"] = jnp.where(take_cur, cur["last"], prev["last"])
        out["last_ts"] = jnp.where(take_cur, cur["last_ts"],
                                   prev["last_ts"])
    return out


def finalize_aggregate(partial: dict, which: tuple = ALL_AGGS) -> dict:
    """Turn combined partial grids into user-facing aggregates.
    Empty cells: count 0, sum 0, min +inf, max -inf, avg/last NaN.
    Emits the requested aggregates that `partial` can supply (count is
    always present)."""
    want = set(which) | {"count"}
    count = partial["count"]
    empty = count == 0
    nan = jnp.float32(jnp.nan)
    out = {"count": count}
    if "sum" in partial and "sum" in want:
        out["sum"] = partial["sum"]
    if "sum" in partial and "avg" in want:
        out["avg"] = jnp.where(empty, nan,
                               partial["sum"] / jnp.maximum(count, 1.0))
    if "min" in partial and "min" in want:
        out["min"] = partial["min"]
    if "max" in partial and "max" in want:
        out["max"] = partial["max"]
    if "last" in partial and "last" in want:
        out["last"] = jnp.where(empty, nan, partial["last"])
    return out


_IMPLS = ("xla", "pallas")
_impl = "xla"


def downsample_impl() -> str:
    """The selected fused-downsample implementation (see
    set_downsample_impl) — read by ops/device_decode.py so the fused
    decode dispatch rides the same measured-before-adoption knob."""
    return _impl


def set_downsample_impl(name: str) -> None:
    """Select the fused downsample implementation: "xla" (segment ops,
    the default) or "pallas" (ops.pallas_kernels compare-broadcast
    kernel; interpret mode is used automatically off-TPU).  The default
    flips only when the hardware benchmark says the kernel wins —
    measured, not assumed."""
    if name not in _IMPLS:
        raise ValueError(f"unknown downsample impl {name!r}; "
                         f"expected one of {_IMPLS}")
    global _impl
    _impl = name


# route the env knob through the setter so typos fail at import instead
# of silently running the XLA path
set_downsample_impl(os.environ.get("HORAEDB_DOWNSAMPLE_IMPL", "xla"))


def time_bucket_aggregate(ts_offset: jax.Array, group_ids: jax.Array,
                          values: jax.Array, n_valid, bucket_ms,
                          num_groups: int, num_buckets: int,
                          which: tuple = ALL_AGGS) -> dict:
    """See _time_bucket_aggregate_impl; this thin wrapper canonicalizes
    `which` so permutations/duplicates share one compiled program, and
    dispatches to the Pallas kernel when selected."""
    which = tuple(sorted(set(which)))
    unknown = set(which) - set(ALL_AGGS)
    if unknown:
        raise ValueError(f"unknown aggregates {sorted(unknown)}; "
                         f"supported: {ALL_AGGS}")
    if _impl == "pallas":
        from horaedb_tpu.ops.pallas_kernels import (
            pallas_time_bucket_aggregate,
        )

        try:
            return pallas_time_bucket_aggregate(
                ts_offset, group_ids, values, n_valid, bucket_ms,
                num_groups=num_groups, num_buckets=num_buckets,
                which=which,
                interpret=jax.devices()[0].platform != "tpu")
        except Exception as exc:  # noqa: BLE001 — guarded, classified
            # explicit reason reporting instead of a bare swallow:
            # CPU-only CI must be able to tell "this box has no TPU"
            # (interpret-mode gap, an environment fact) from a real
            # kernel bug on hardware (docs/observability.md,
            # scan_decode_fallback_total)
            from horaedb_tpu.ops import device_decode

            reason = device_decode.classify_pallas_failure()
            device_decode.note_fallback(reason)
            logging.getLogger(__name__).warning(
                "pallas downsample kernel failed (%s): %s; "
                "serving the XLA path", reason, exc)
    return _time_bucket_aggregate_impl(
        ts_offset, group_ids, values, n_valid, bucket_ms,
        num_groups=num_groups, num_buckets=num_buckets, which=which)


@deviceprof.jit(static_argnames=("num_groups", "num_buckets", "which"))
def _time_bucket_aggregate_impl(ts_offset: jax.Array, group_ids: jax.Array,
                                values: jax.Array, n_valid, bucket_ms,
                                num_groups: int, num_buckets: int,
                                which: tuple = ALL_AGGS) -> dict:
    """Single-shard aggregate: partial + finalize in one compiled program.

    Args:
      ts_offset: int32 (capacity,) — timestamp offsets from the query range
        start (so bucket 0 starts at offset 0).
      group_ids: int32 (capacity,) — dictionary codes of the group key.
      values: float32 (capacity,).
      n_valid: scalar int — real row count.
      bucket_ms: scalar int32 — bucket width in the ts unit.
      num_groups / num_buckets: static grid extents.

    Returns a dict of (num_groups, num_buckets) float32 grids holding
    `count` plus the aggregates requested via `which` (default: sum,
    min, max, avg, last — `last` is the value at max ts per cell).
    """
    return finalize_aggregate(
        partial_aggregate(ts_offset, group_ids, values, n_valid, bucket_ms,
                          num_groups, num_buckets, which=which),
        which=which)
