"""Process-global memory ledger: where is my RAM, and how close am I
to the cliff? (docs/observability.md, memory plane)

The process has rich *time* observability (traces, the loop registry)
but its byte-holding structures — the HBM windows cache, the tier-2
encoded cache, the parts memo, pipeline in-flight buffers, memtables,
the WAL backlog, streamed-SST mmaps, scan-agent wire buffers, the
in-memory object store — each count their own bytes privately.  The
ledger is the single place they all report to, so ONE number answers
what nothing else can:

    unattributed = RSS − Σ accounts

A big positive unattributed is a leak or a structure nobody registered;
a negative one is double counting.  Either way it is visible, which is
the whole point: the 1B-row ladder (ROADMAP item 3) breaks first on
resident memory, and "projected from hand math" is not an alert.

Two account shapes, mirroring how components actually hold bytes:

  pull accounts   budget-owning structures that already track their
                  residency (ByteLRU.total_bytes, memtable bytes, WAL
                  backlog).  `register(name, fn, anchor=owner_obj)`
                  stores a plain function called as fn(owner) against a
                  WEAK reference to the owner — the ledger never keeps
                  a dead cache's contents alive, and an owner that was
                  dropped without an explicit deregister (tests,
                  abandoned stores) prunes on the next sweep exactly
                  like a dead loop handle.
  flow accounts   transient byte flows with no single resident owner
                  (streamed-SST mmaps in flight, scanagent partials on
                  the wire).  `flow(name)` returns an account the call
                  sites charge()/credit(); balanced teardown MUST
                  return it to zero (tested).

Account *names* are unique instances (per table root); the metric
label is the KIND (prefix before ":"), exactly the loop registry's
label discipline — per-table names embed temp paths and would be
unbounded label values.  `memory_account_bytes{account=<kind>}`,
`memory_rss_bytes`, and `memory_unattributed_bytes` land in the
registry, so the meta-ingest loop makes memory history queryable and
rollup-served for free.

An RSS sampler loop (spawned through loops.spawn — PR-7 discipline:
heartbeats, watchdog, /debug/tasks) reads /proc/self/status VmRSS
(plus smaps_rollup where the kernel has it) every `[memory] interval`,
republishes every account gauge, and evaluates soft/hard pressure
watermarks: `memory_pressure` is 0/1/2 and
`memory_pressure_transitions_total{level=}` fires ONCE per episode
(watchdog-style), with a hysteresis band so a process breathing at the
watermark doesn't flap.  `GET /debug/memory` serves the full account
tree with budgets/utilization/high-water.
"""

from __future__ import annotations

import asyncio
import logging
import sys
import threading
import time
import weakref
from typing import Callable, Optional

from horaedb_tpu.utils.metrics import registry

logger = logging.getLogger(__name__)
slow_logger = logging.getLogger("horaedb_tpu.trace.slow")

_ACCOUNT_BYTES = registry.gauge(
    "memory_account_bytes",
    "resident host bytes attributed to a ledger account kind "
    "(updated each sampler round)")
_RSS = registry.gauge(
    "memory_rss_bytes", "process resident set size (/proc/self/status)")
_UNATTRIBUTED = registry.gauge(
    "memory_unattributed_bytes",
    "RSS minus the sum of all ledger accounts: leaks and unregistered "
    "structures show up positive, double counting negative")
_ATTRIBUTED = registry.gauge(
    "memory_attributed_bytes", "sum of all ledger accounts")
_PRESSURE = registry.gauge(
    "memory_pressure",
    "memory pressure level: 0 below soft, 1 at/over soft, 2 at/over "
    "hard watermark")
_TRANSITIONS = registry.counter(
    "memory_pressure_transitions_total",
    "pressure episodes entered, once per episode, by level "
    "(soft|hard)")
_DEVICE_BYTES = registry.gauge(
    "memory_device_bytes",
    "accelerator bytes in use per device (jax memory_stats; absent on "
    "CPU backends and older jax)")
_DEVICE_HIGH_WATER = registry.gauge(
    "memory_device_high_water_bytes",
    "peak accelerator bytes in use per device since engine open "
    "(sampled high-water; reset to 0 on engine close)")


def read_rss_bytes() -> Optional[int]:
    """VmRSS from /proc/self/status, or None off-Linux."""
    try:
        with open("/proc/self/status", "rb") as f:
            for line in f:
                if line.startswith(b"VmRSS:"):
                    return int(line.split()[1]) * 1024
    except OSError:
        return None
    return None


def read_smaps_rollup() -> dict:
    """Selected fields of /proc/self/smaps_rollup (kernel 4.14+) in
    bytes — the private/shared/anon split that tells mmap'd SST streams
    apart from heap.  Empty dict where the kernel lacks the file."""
    want = (b"Rss:", b"Pss:", b"Shared_Clean:", b"Shared_Dirty:",
            b"Private_Clean:", b"Private_Dirty:", b"Anonymous:")
    out = {}
    try:
        with open("/proc/self/smaps_rollup", "rb") as f:
            for line in f:
                for key in want:
                    if line.startswith(key):
                        out[key[:-1].decode().lower()] = (
                            int(line.split()[1]) * 1024)
    except OSError:
        return {}
    return out


def read_meminfo_total() -> Optional[int]:
    """MemTotal in bytes (watermark auto-derivation), or None."""
    try:
        with open("/proc/meminfo", "rb") as f:
            for line in f:
                if line.startswith(b"MemTotal:"):
                    return int(line.split()[1]) * 1024
    except OSError:
        return None
    return None


def device_memory() -> list[dict]:
    """Per-device live bytes from jax, guarded three ways: jax not yet
    imported (probing would initialize a backend — the cpu_mesh
    discipline), devices unavailable, and memory_stats absent/None
    (CPU backends and older jax return nothing)."""
    if "jax" not in sys.modules:
        return []
    jax = sys.modules["jax"]
    try:
        devices = jax.devices()
    except Exception:  # noqa: BLE001 — no backend is a valid state
        return []
    out = []
    for d in devices:
        stats_fn = getattr(d, "memory_stats", None)
        if stats_fn is None:
            continue
        try:
            stats = stats_fn()
        except Exception:  # noqa: BLE001 — backend quirk, not an error
            stats = None
        if not stats or "bytes_in_use" not in stats:
            continue
        out.append({
            "device": f"{d.platform}:{d.id}",
            "bytes_in_use": int(stats["bytes_in_use"]),
            "bytes_limit": int(stats.get("bytes_limit", 0)) or None,
            "peak_bytes_in_use": (
                int(stats["peak_bytes_in_use"])
                if "peak_bytes_in_use" in stats else None),
        })
    return out


class MemAccount:
    """One byte-holding component's ledger entry.

    Pull accounts hold (weak anchor, fn); `bytes()` derefs the anchor
    and returns None once the owner died — the sweep prunes it.  Flow
    accounts have no anchor; charge()/credit() move the balance (int
    adds under a lock: charged from pool threads and the event loop
    alike)."""

    __slots__ = ("name", "kind", "owner", "budget", "high_water",
                 "host", "_anchor", "_fn", "_balance", "_lock",
                 "__weakref__")

    def __init__(self, name: str, kind: str, owner: str,
                 budget: Optional[int],
                 fn: Optional[Callable] = None,
                 anchor: Optional[object] = None,
                 host: bool = True):
        self.name = name
        self.kind = kind
        self.owner = owner
        self.budget = budget
        self.high_water = 0
        # host=False: the bytes live on an ACCELERATOR (HBM stacks on
        # a TPU backend) — tracked and reported per kind, but excluded
        # from the attributed total that is subtracted from host RSS
        # (they are not host RSS; memory_device_bytes covers the
        # device side, and double-subtracting would push unattributed
        # negative by exactly their size)
        self.host = host
        self._fn = fn
        self._anchor = weakref.ref(anchor) if anchor is not None else None
        self._balance = 0
        self._lock = threading.Lock()

    @property
    def is_flow(self) -> bool:
        return self._fn is None

    def charge(self, nbytes: int) -> None:
        """Flow accounts only: bytes taken (a stream fetch started, a
        wire partial buffered)."""
        if nbytes <= 0:
            return
        with self._lock:
            self._balance += nbytes
            if self._balance > self.high_water:
                self.high_water = self._balance

    def credit(self, nbytes: int) -> None:
        """Flow accounts only: bytes returned."""
        if nbytes <= 0:
            return
        with self._lock:
            self._balance -= nbytes

    def bytes(self) -> Optional[int]:
        """Current resident bytes, or None when the pull account's
        owner is gone (prune me)."""
        if self._fn is None:
            return self._balance
        if self._anchor is not None:
            obj = self._anchor()
            if obj is None:
                return None
            try:
                return int(self._fn(obj))
            except Exception:  # noqa: BLE001 — a mid-close race reads 0
                return 0
        try:
            return int(self._fn())
        except Exception:  # noqa: BLE001
            return 0

    def dead(self) -> bool:
        return self._anchor is not None and self._anchor() is None


class MemoryLedger:
    """Process-global account registry + RSS sampler + pressure
    watermarks ([memory] config).  Like the loop registry: one per
    process, components register at open and deregister at close, a
    lazily-started loop sweeps."""

    def __init__(self, clock=time.monotonic,
                 rss_reader: Callable[[], Optional[int]] = read_rss_bytes
                 ) -> None:
        self._clock = clock
        self._rss_reader = rss_reader
        self._accounts: dict[str, MemAccount] = {}
        self._lock = threading.Lock()
        self._sampler_task: Optional[asyncio.Task] = None
        # kinds whose account gauge a past sweep wrote: a kind whose
        # accounts all deregistered must be zeroed, not left serving
        # its last bytes forever (the loop registry's _hb_kinds
        # discipline) — same for per-device gauges
        self._gauge_kinds: set[str] = set()
        self._device_labels: set[str] = set()
        # sampled per-device peaks; survive label absence (a device that
        # freed everything keeps its peak) until reset on engine close
        self._device_high_water: dict[str, int] = {}
        self.enabled = True
        self.interval_s = 5.0
        # 0 = derive from MemTotal at configure time (soft 70%, hard
        # 85%); None = no watermark (pressure pinned at 0)
        self.soft_bytes: Optional[int] = None
        self.hard_bytes: Optional[int] = None
        self.hysteresis = 0.05
        self.pressure_level = 0
        self.pressure_episodes = {"soft": 0, "hard": 0}
        self._last_sample: Optional[dict] = None
        self._last_sample_at: Optional[float] = None

    # ---- configuration ----------------------------------------------------

    def configure(self, enabled: Optional[bool] = None,
                  interval_s: Optional[float] = None,
                  soft_bytes: Optional[int] = None,
                  hard_bytes: Optional[int] = None,
                  hysteresis: Optional[float] = None) -> None:
        """[memory] config.  soft/hard semantics: None leaves the
        current value, 0 auto-derives from MemTotal (soft 70%, hard
        85%), a negative value disables that watermark."""
        if enabled is not None:
            self.enabled = enabled
        if interval_s is not None:
            self.interval_s = max(0.01, interval_s)
        if hysteresis is not None:
            self.hysteresis = min(0.5, max(0.0, hysteresis))
        total = None
        if soft_bytes == 0 or hard_bytes == 0:
            total = read_meminfo_total()

        def resolve(value: int, frac: float) -> Optional[int]:
            if value < 0:
                return None  # watermark explicitly disabled
            if value == 0:  # auto: fraction of the box's MemTotal
                return int(total * frac) if total else None
            return value

        if soft_bytes is not None:
            self.soft_bytes = resolve(soft_bytes, 0.70)
        if hard_bytes is not None:
            self.hard_bytes = resolve(hard_bytes, 0.85)

    # ---- registration -----------------------------------------------------

    def _insert(self, acct: MemAccount) -> MemAccount:
        with self._lock:
            base, n = acct.name, 2
            while (acct.name in self._accounts
                   and not self._accounts[acct.name].dead()):
                # two engines over the same root must not share one
                # account (the loop registry's #n discipline)
                acct.name = f"{base}#{n}"
                n += 1
            self._accounts[acct.name] = acct
        self.ensure_sampler()
        return acct

    def register(self, name: str, fn: Callable, *,
                 anchor: Optional[object] = None,
                 kind: Optional[str] = None,
                 budget: Optional[int] = None,
                 owner: str = "", host: bool = True) -> MemAccount:
        """Pull account for a budget-owning structure.  `fn` is called
        as fn(anchor) when an anchor is given (the ledger holds the
        anchor WEAKLY — pass the owning object, close over nothing) and
        as fn() otherwise (module-global sources only).  host=False
        for structures resident on an accelerator, not in host RSS."""
        if kind is None:
            kind = name.split(":", 1)[0].split("#", 1)[0]
        return self._insert(MemAccount(name, kind, owner, budget,
                                       fn=fn, anchor=anchor, host=host))

    def flow(self, name: str, *, kind: Optional[str] = None,
             budget: Optional[int] = None, owner: str = "") -> MemAccount:
        """Flow account for transient byte flows: call sites
        charge()/credit(); teardown must balance back to zero."""
        if kind is None:
            kind = name.split(":", 1)[0].split("#", 1)[0]
        return self._insert(MemAccount(name, kind, owner, budget))

    def deregister(self, acct: Optional[MemAccount]) -> None:
        if acct is None:
            return
        with self._lock:
            if self._accounts.get(acct.name) is acct:
                del self._accounts[acct.name]

    def accounts(self) -> list[MemAccount]:
        with self._lock:
            return list(self._accounts.values())

    def get(self, name: str) -> Optional[MemAccount]:
        with self._lock:
            return self._accounts.get(name)

    def kinds(self) -> set[str]:
        """Live account kinds (the enumerate-and-assert test's
        surface)."""
        return {a.kind for a in self.accounts() if not a.dead()}

    # ---- sampling ---------------------------------------------------------

    def attributed(self) -> tuple[int, dict[str, int], list]:
        """(Σ host accounts, per-kind sums, [(account, bytes)]) in ONE
        walk; prunes dead pull accounts.  Non-host (accelerator)
        accounts report per kind but stay out of the total — they are
        not host RSS and would push unattributed negative."""
        per_kind: dict[str, int] = {}
        detail: list = []
        total = 0
        for acct in self.accounts():
            b = acct.bytes()
            if b is None:
                self.deregister(acct)
                continue
            if b > acct.high_water:
                acct.high_water = b
            per_kind[acct.kind] = per_kind.get(acct.kind, 0) + b
            detail.append((acct, b))
            if acct.host:
                total += b
        return total, per_kind, detail

    def sample_once(self, rss: Optional[int] = None) -> dict:
        """One sampler round (callable directly from tests/handlers):
        republish account gauges, read RSS, compute unattributed,
        evaluate pressure.  `rss` overrides the /proc read (tests)."""
        total, per_kind, detail = self.attributed()
        for kind, b in per_kind.items():
            _ACCOUNT_BYTES.labels(account=kind).set(b)
        for kind in self._gauge_kinds - set(per_kind):
            _ACCOUNT_BYTES.labels(account=kind).set(0)
        self._gauge_kinds = set(per_kind)
        _ATTRIBUTED.set(total)

        if rss is None:
            rss = self._rss_reader()
        out = {"attributed_bytes": total, "accounts": per_kind,
               "account_detail": detail, "rss_bytes": rss,
               "unattributed_bytes": None}
        if rss is not None:
            _RSS.set(rss)
            out["unattributed_bytes"] = rss - total
            _UNATTRIBUTED.set(rss - total)
            self._eval_pressure(rss)
        out["pressure"] = self.pressure_level

        devices = device_memory()
        labels = set()
        for d in devices:
            dev = d["device"]
            b = d["bytes_in_use"]
            hw = max(self._device_high_water.get(dev, 0), b)
            self._device_high_water[dev] = hw
            d["high_water_bytes"] = hw
            _DEVICE_BYTES.labels(device=dev).set(b)
            _DEVICE_HIGH_WATER.labels(device=dev).set(hw)
            labels.add(dev)
        for label in self._device_labels - labels:
            _DEVICE_BYTES.labels(device=label).set(0)
        self._device_labels = labels
        out["devices"] = devices

        self._last_sample = out
        self._last_sample_at = self._clock()
        return out

    def _eval_pressure(self, rss: int) -> None:
        """Watermark check with hysteresis: escalate the moment RSS
        crosses a watermark (counting ONE episode per level entered),
        de-escalate only once RSS drops below the current level's
        watermark by the hysteresis margin — a process breathing at
        the line is one episode, not a counter flood."""
        soft, hard = self.soft_bytes, self.hard_bytes
        raw = (2 if hard is not None and rss >= hard else
               1 if soft is not None and rss >= soft else 0)
        lvl = self.pressure_level
        if raw > lvl:
            if raw == 2 and lvl < 2:
                self.pressure_episodes["hard"] += 1
                _TRANSITIONS.labels(level="hard").inc()
                slow_logger.warning(
                    "[memory] HARD pressure: rss=%d >= hard=%d "
                    "(unattributed and per-account bytes on "
                    "/debug/memory)", rss, hard)
            if raw >= 1 and lvl < 1:
                self.pressure_episodes["soft"] += 1
                _TRANSITIONS.labels(level="soft").inc()
                if raw == 1:
                    slow_logger.warning(
                        "[memory] soft pressure: rss=%d >= soft=%d",
                        rss, soft)
            lvl = raw
        elif raw < lvl:
            wm = hard if lvl == 2 else soft
            if wm is None or rss < wm * (1.0 - self.hysteresis):
                lvl = raw
        self.pressure_level = lvl
        _PRESSURE.set(lvl)

    # ---- the sampler loop -------------------------------------------------

    def ensure_sampler(self) -> None:
        """Lazy-start the RSS sampler on the CURRENT event loop (the
        watchdog's ensure pattern: a task stranded on a closed loop is
        abandoned — its loop handle prunes — and replaced; no running
        loop is a no-op, the next register from async context
        starts it)."""
        try:
            running = asyncio.get_running_loop()
        except RuntimeError:
            return
        t = self._sampler_task
        if t is not None and not t.done():
            try:
                if t.get_loop() is running:
                    return
                if not t.get_loop().is_closed():
                    return  # a live sampler elsewhere still sweeps
            except RuntimeError:
                pass
        from horaedb_tpu.common.loops import loops

        self._sampler_task = loops.spawn(
            self._sampler_loop, name="mem-sampler",
            period_s=self.interval_s, owner="memledger")

    async def _sampler_loop(self, hb) -> None:
        while True:
            hb.beat()
            try:
                if self.enabled:
                    self.sample_once()
                hb.ok()
            except Exception as exc:  # noqa: BLE001 — sample next round
                hb.error(exc)
                logger.exception("memory sampler round failed")
            await asyncio.sleep(self.interval_s)

    # ---- the /debug/memory + /stats surface -------------------------------

    def snapshot(self) -> dict:
        """The full account tree (GET /debug/memory): per-kind rollups
        with budgets/utilization/high-water, instance detail, RSS,
        unattributed, pressure state, device memory, smaps extras.
        Samples explicitly — a /debug request wants CURRENT numbers
        even with the background sampler disabled — and the tree is
        built from the SAME walk as the totals beside it, so they
        cannot disagree."""
        sample = self.sample_once()
        groups: dict[str, dict] = {}
        for acct, b in sample["account_detail"]:
            g = groups.setdefault(acct.kind, {
                "bytes": 0, "budget": 0, "high_water": 0,
                "host": acct.host, "instances": []})
            g["bytes"] += b
            g["high_water"] += acct.high_water
            if acct.budget is not None:
                g["budget"] += acct.budget
            g["instances"].append({
                "name": acct.name, "owner": acct.owner, "bytes": b,
                "budget": acct.budget, "high_water": acct.high_water,
                "flow": acct.is_flow})
        for g in groups.values():
            if g["budget"]:
                g["utilization"] = round(g["bytes"] / g["budget"], 4)
            else:
                g["budget"] = None
            g["instances"].sort(key=lambda i: -i["bytes"])
        rss = sample["rss_bytes"]
        return {
            "rss_bytes": rss,
            "attributed_bytes": sample["attributed_bytes"],
            "unattributed_bytes": sample["unattributed_bytes"],
            "unattributed_fraction": (
                round(sample["unattributed_bytes"] / rss, 4)
                if rss else None),
            "pressure": {
                "level": self.pressure_level,
                "soft_bytes": self.soft_bytes,
                "hard_bytes": self.hard_bytes,
                "hysteresis": self.hysteresis,
                "episodes": dict(self.pressure_episodes),
            },
            "accounts": dict(sorted(groups.items())),
            "devices": sample["devices"],
            "smaps_rollup": read_smaps_rollup() or None,
            "sampler": {
                "enabled": self.enabled,
                "interval_s": self.interval_s,
            },
        }

    def summary(self) -> dict:
        """Compact rollup for /stats: totals + per-kind bytes, no
        instance detail.  Serves the sampler's last round when fresh
        (a /stats poll must not duplicate sampler work), resamples
        when stale.  DISABLED ([memory] enabled = false) means no
        sampling work on the /stats path at all — the last sample (if
        any) is served as-is, marked disabled; an operator who turned
        the plane off must not pay per-poll ledger walks instead."""
        if not self.enabled:
            sample = self._last_sample or {}
            return {
                "enabled": False,
                "rss_bytes": sample.get("rss_bytes"),
                "attributed_bytes": sample.get("attributed_bytes"),
                "unattributed_bytes": sample.get("unattributed_bytes"),
                "pressure": self.pressure_level,
                "accounts": dict(sorted(
                    sample.get("accounts", {}).items())),
            }
        fresh = (self._last_sample is not None
                 and self._last_sample_at is not None
                 and self._clock() - self._last_sample_at
                 < 2 * self.interval_s)
        sample = self._last_sample if fresh else self.sample_once()
        return {
            "rss_bytes": sample["rss_bytes"],
            "attributed_bytes": sample["attributed_bytes"],
            "unattributed_bytes": sample["unattributed_bytes"],
            "pressure": self.pressure_level,
            "accounts": dict(sorted(sample["accounts"].items())),
            "device_high_water": dict(sorted(
                self._device_high_water.items())),
        }

    def reset_device_high_water(self) -> None:
        """Engine close resets the per-device peaks (clear-on-close
        discipline): the next engine's high-water marks are its own,
        not inherited from a table that no longer exists."""
        for dev in self._device_high_water:
            _DEVICE_HIGH_WATER.labels(device=dev).set(0)
        self._device_high_water = {}


ledger = MemoryLedger()
