"""Human-readable byte sizes ("2GB", "512MiB") for config files.

Mirrors the reference's `ReadableSize` (ref: src/common/src/size_ext.rs:27-165):
binary multipliers (KB == KiB == 1024 bytes), optional fractional values,
bare numbers mean bytes.
"""

from __future__ import annotations

import re

from horaedb_tpu.common.error import Error

_UNIT_B = 1
_UNIT_KB = 1024
_UNIT_MB = 1024**2
_UNIT_GB = 1024**3
_UNIT_TB = 1024**4
_UNIT_PB = 1024**5

_SUFFIXES = {
    "": _UNIT_B,
    "b": _UNIT_B,
    "k": _UNIT_KB,
    "kb": _UNIT_KB,
    "kib": _UNIT_KB,
    "m": _UNIT_MB,
    "mb": _UNIT_MB,
    "mib": _UNIT_MB,
    "g": _UNIT_GB,
    "gb": _UNIT_GB,
    "gib": _UNIT_GB,
    "t": _UNIT_TB,
    "tb": _UNIT_TB,
    "tib": _UNIT_TB,
    "p": _UNIT_PB,
    "pb": _UNIT_PB,
    "pib": _UNIT_PB,
}

_SIZE_RE = re.compile(r"^\s*(\d+(?:\.\d*)?)\s*([a-z]*)\s*$")


class ReadableSize:
    __slots__ = ("bytes",)

    def __init__(self, num_bytes: int):
        if num_bytes < 0:
            raise Error(f"size must be non-negative, got {num_bytes}")
        self.bytes = int(num_bytes)

    @classmethod
    def parse(cls, s: str) -> "ReadableSize":
        m = _SIZE_RE.match(s.lower())
        if m is None:
            raise Error(f"invalid size string: {s!r}")
        value, suffix = float(m.group(1)), m.group(2)
        if suffix not in _SUFFIXES:
            raise Error(f"unknown size suffix in: {s!r}")
        return cls(round(value * _SUFFIXES[suffix]))

    @classmethod
    def kb(cls, n: int) -> "ReadableSize":
        return cls(n * _UNIT_KB)

    @classmethod
    def mb(cls, n: int) -> "ReadableSize":
        return cls(n * _UNIT_MB)

    @classmethod
    def gb(cls, n: int) -> "ReadableSize":
        return cls(n * _UNIT_GB)

    def __eq__(self, other: object) -> bool:
        return isinstance(other, ReadableSize) and other.bytes == self.bytes

    def __hash__(self) -> int:
        return hash(self.bytes)

    def __repr__(self) -> str:
        return f"ReadableSize({self})"

    def __str__(self) -> str:
        for suffix, unit in (("PB", _UNIT_PB), ("TB", _UNIT_TB), ("GB", _UNIT_GB),
                             ("MB", _UNIT_MB), ("KB", _UNIT_KB)):
            if self.bytes >= unit and self.bytes % unit == 0:
                return f"{self.bytes // unit}{suffix}"
        return f"{self.bytes}B"
