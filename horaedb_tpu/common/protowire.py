"""Minimal proto3 wire-format codec.

The reference's manifest delta files are prost-encoded `ManifestUpdate`
messages (ref: src/pb_types/protos/sst.proto:24-47, manifest/mod.rs:133-137).
Rather than depending on generated bindings we implement the handful of
wire primitives proto3 needs — varints, length-delimited fields, packed
repeated scalars — so our delta files are byte-compatible with prost's
output (proto3 rules: default-valued scalar fields are omitted; repeated
scalars are packed).
"""

from __future__ import annotations

from horaedb_tpu.common.error import Error

WIRE_VARINT = 0
WIRE_LEN = 2

_U64_MASK = (1 << 64) - 1


def encode_varint(value: int, out: bytearray) -> None:
    if value < 0:
        raise Error(f"varint must be non-negative, got {value}")
    while True:
        byte = value & 0x7F
        value >>= 7
        if value:
            out.append(byte | 0x80)
        else:
            out.append(byte)
            return


def decode_varint(buf: bytes, pos: int) -> tuple[int, int]:
    result = 0
    shift = 0
    while True:
        if pos >= len(buf):
            raise Error("truncated varint")
        byte = buf[pos]
        pos += 1
        result |= (byte & 0x7F) << shift
        if not byte & 0x80:
            if result > _U64_MASK:
                raise Error("varint overflows u64")
            return result, pos
        shift += 7
        if shift >= 64:
            raise Error("varint too long")


def encode_tag(field_number: int, wire_type: int, out: bytearray) -> None:
    encode_varint((field_number << 3) | wire_type, out)


def decode_tag(buf: bytes, pos: int) -> tuple[int, int, int]:
    tag, pos = decode_varint(buf, pos)
    return tag >> 3, tag & 0x7, pos


def encode_u64_field(field_number: int, value: int, out: bytearray) -> None:
    """uint64 field; proto3 omits zero values."""
    if value == 0:
        return
    encode_tag(field_number, WIRE_VARINT, out)
    encode_varint(value, out)


def encode_i64_field(field_number: int, value: int, out: bytearray) -> None:
    """int64 field; negatives sign-extend to a 10-byte varint."""
    if value == 0:
        return
    encode_tag(field_number, WIRE_VARINT, out)
    encode_varint(value & _U64_MASK, out)


def decode_i64(value: int) -> int:
    """Reinterpret a decoded u64 varint as two's-complement i64."""
    return value - (1 << 64) if value >= (1 << 63) else value


def encode_len_field(field_number: int, payload: bytes, out: bytearray) -> None:
    encode_tag(field_number, WIRE_LEN, out)
    encode_varint(len(payload), out)
    out.extend(payload)


def encode_packed_u64_field(field_number: int, values: list[int], out: bytearray) -> None:
    if not values:
        return
    payload = bytearray()
    for v in values:
        encode_varint(v, payload)
    encode_len_field(field_number, bytes(payload), out)


def skip_field(buf: bytes, pos: int, wire_type: int) -> int:
    if wire_type == WIRE_VARINT:
        _, pos = decode_varint(buf, pos)
        return pos
    if wire_type == WIRE_LEN:
        length, pos = decode_varint(buf, pos)
        if pos + length > len(buf):
            raise Error("truncated length-delimited field")
        return pos + length
    if wire_type == 1:  # 64-bit
        return pos + 8
    if wire_type == 5:  # 32-bit
        return pos + 4
    raise Error(f"unsupported wire type: {wire_type}")


def read_len_payload(buf: bytes, pos: int) -> tuple[bytes, int]:
    length, pos = decode_varint(buf, pos)
    if pos + length > len(buf):
        raise Error("truncated length-delimited field")
    return buf[pos : pos + length], pos + length
