"""Common utilities shared by every layer (ref: src/common)."""

from horaedb_tpu.common.deadline import Deadline, DeadlineExceeded
from horaedb_tpu.common.error import Error, ensure
from horaedb_tpu.common.size_ext import ReadableSize
from horaedb_tpu.common.tasks import cancel_and_wait
from horaedb_tpu.common.time_ext import ReadableDuration, now_ms

__all__ = ["Deadline", "DeadlineExceeded", "Error", "ensure",
           "ReadableDuration", "ReadableSize", "cancel_and_wait",
           "now_ms"]
