"""Device-plane profiler: compile ledger, dispatch profiler, transfer
accounting (docs/observability.md, device plane).

The query-trace, background-loop, and memory planes cover the host;
this registry covers the DEVICE: every `jax.jit` seam in the package
wraps through `deviceprof.jit(...)` (tools/lint.py rejects bare
jax.jit under horaedb_tpu/), which gives each compiled function a
ledger entry answering the three questions XLA keeps to itself:

  did it compile?   per-fn compile count + cumulative compile seconds
                    + the triggering cache key (arg shapes/dtypes and
                    static values), so a recompile names the dimension
                    that churned instead of "it was slow once"
  where did the wall go?   per-dispatch host time (trace/cache-lookup/
                    enqueue) vs device execution (measured at the
                    existing block_until_ready seams) — a cold query's
                    slow-log entry states whether it paid compilation,
                    dispatch overhead, or the kernel
  what moved?       device_transfer_bytes_total{direction=h2d|d2h}
                    charged at the device_put/download seams, with
                    per-trace twins, reconciled against the memory
                    ledger's device accounts

Recompile STORMS (N compiles of one fn inside a sliding window — the
shape-churn failure mode of a capacity-padded engine) flag once per
episode, watchdog-style: `device_recompile_storms_total{fn=}` plus a
slow-log line naming the churning key dimension.  The episode clears
when the window drains; the next storm is a new episode.

The profiler also keeps the mesh ROUND timeline: per-round slot fill
ratio, padding-waste rows, and per-shard row imbalance — the batching
quality the [scan.mesh] dispatcher achieved, served with the compile
table, transfer totals, and per-device memory on `GET /debug/device`.

Process-global (like utils.metrics.registry / utils.tracing.recorder /
common.loops.loops / common.memledger.ledger).  All families ride the
clear-on-close discipline: `profiler.clear()` at engine close removes
every labeled child so a closed engine serves no phantom device
series.  Wrappers stay registered — the compiled functions are
module-level and outlive any one engine; only their accounted state
resets.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Any, Callable, Optional

from horaedb_tpu.utils.metrics import registry
from horaedb_tpu.utils.tracing import trace_add

logger = logging.getLogger(__name__)
# storms land next to slow queries and watchdog stalls: one stream an
# operator greps for "the system is not keeping up" events
slow_logger = logging.getLogger("horaedb_tpu.trace.slow")

_COMPILES = registry.counter(
    "device_compiles_total",
    "XLA compilations per jitted function (deviceprof.jit seams)")
_COMPILE_SECONDS = registry.counter(
    "device_compile_seconds_total",
    "cumulative trace+lower+compile wall seconds per jitted function")
_STORMS = registry.counter(
    "device_recompile_storms_total",
    "recompile-storm episodes per jitted function (N compiles inside "
    "the [deviceprof] sliding window, flagged once per episode)")
_DISPATCHES = registry.counter(
    "device_dispatches_total",
    "cache-hit dispatches per jitted function (compiling calls count "
    "under device_compiles_total instead)")
_DISPATCH_SECONDS = registry.histogram(
    "device_dispatch_seconds",
    "host-side dispatch wall per cached call (trace-cache lookup + "
    "argument processing + async enqueue), per jitted function")
_EXEC_SECONDS = registry.histogram(
    "device_exec_seconds",
    "device execution wall measured at block_until_ready seams, per "
    "jitted function")
_TRANSFER_BYTES = registry.counter(
    "device_transfer_bytes_total",
    "bytes moved across the host/device boundary at the device_put "
    "and download seams, by direction (h2d|d2h)")
_TRANSFER_SECONDS = registry.counter(
    "device_transfer_seconds_total",
    "wall seconds spent in instrumented host/device transfers, by "
    "direction (h2d|d2h; async puts charge the enqueue wall)")


def _nbytes(x: Any) -> int:
    """Total payload bytes of an array pytree (tuples/lists/dicts of
    array-likes; scalars and static leaves count zero)."""
    if x is None:
        return 0
    nb = getattr(x, "nbytes", None)
    if nb is not None:
        return int(nb)
    if isinstance(x, (tuple, list)):
        return sum(_nbytes(v) for v in x)
    if isinstance(x, dict):
        return sum(_nbytes(v) for v in x.values())
    return 0


def _leaf_key(label: str, x: Any, out: list) -> None:
    """Flatten one call argument into labeled cache-key components.
    Arrays contribute (label.shape, label.dtype); containers recurse
    with indexed labels; everything else is a static VALUE component —
    exactly the dimensions jit's own cache keys on, labeled so a storm
    can name the one that churns."""
    if hasattr(x, "shape") and hasattr(x, "dtype"):
        out.append((f"{label}.shape", tuple(x.shape)))
        out.append((f"{label}.dtype", str(x.dtype)))
    elif isinstance(x, (tuple, list)):
        for j, v in enumerate(x):
            _leaf_key(f"{label}[{j}]", v, out)
    elif isinstance(x, dict):
        for k in sorted(x):
            _leaf_key(f"{label}.{k}", x[k], out)
    else:
        out.append((label, repr(x)))


def _call_key(args: tuple, kwargs: dict) -> tuple:
    out: list = []
    for i, a in enumerate(args):
        _leaf_key(f"a{i}", a, out)
    for k in sorted(kwargs):
        _leaf_key(k, kwargs[k], out)
    return tuple(out)


class FnRecord:
    """One jitted function's ledger entry.  Scalar fields are written
    under the profiler lock; the wrapper holds the record for the
    process's life (clear() resets state, never identity)."""

    __slots__ = ("name", "compiles", "compile_seconds", "last_compile_s",
                 "last_key", "dispatches", "dispatch_seconds",
                 "execs", "exec_seconds", "storms", "storm_active",
                 "_window", "_churn", "_prev_key", "_cache_size",
                 "_keys")

    def __init__(self, name: str) -> None:
        self.name = name
        self.reset()

    def reset(self) -> None:
        self.compiles = 0
        self.compile_seconds = 0.0
        self.last_compile_s = 0.0
        self.last_key: Optional[tuple] = None
        self.dispatches = 0
        self.dispatch_seconds = 0.0
        self.execs = 0
        self.exec_seconds = 0.0
        self.storms = 0
        self.storm_active = False
        self._window: deque = deque()
        self._churn: dict[str, int] = {}
        self._prev_key: Optional[tuple] = None
        # compile detection state survives clear(): jit's own cache is
        # not reset by an engine close, so ours must not be either or
        # every post-close call would double-count as a compile
        if not hasattr(self, "_cache_size"):
            self._cache_size = 0
            self._keys: set = set()

    def snapshot(self) -> dict:
        return {
            "fn": self.name,
            "compiles": self.compiles,
            "compile_seconds": round(self.compile_seconds, 6),
            "last_compile_ms": round(self.last_compile_s * 1e3, 3),
            "last_key": (None if self.last_key is None
                         else {k: repr(v) for k, v in self.last_key}),
            "dispatches": self.dispatches,
            "dispatch_seconds": round(self.dispatch_seconds, 6),
            "execs": self.execs,
            "exec_seconds": round(self.exec_seconds, 6),
            "storms": self.storms,
            "storm_active": self.storm_active,
        }


class ProfiledJit:
    """The callable `deviceprof.jit` returns: jax.jit underneath, the
    ledger on top.  Unknown attributes (lower, eval_shape, trace)
    forward to the jitted function, so AOT call sites keep working."""

    def __init__(self, owner: "DeviceProfiler", fn: Callable, name: str,
                 jit_kwargs: dict) -> None:
        import jax

        self._jitted = jax.jit(fn, **jit_kwargs)  # noqa: the one seam
        self._name = name
        self.__name__ = name
        self.__doc__ = getattr(fn, "__doc__", None)
        self.__wrapped__ = fn
        self._owner = owner
        self._rec = owner._record(name)

    def __call__(self, *args, **kwargs):
        if not self._owner.enabled:
            return self._jitted(*args, **kwargs)
        return self._owner._profiled_call(self._rec, self._jitted,
                                          args, kwargs)

    def __getattr__(self, item: str):
        return getattr(self._jitted, item)

    def __repr__(self) -> str:
        return f"<deviceprof.jit {self._name}>"


class DeviceProfiler:
    """Process-global device-plane registry ([deviceprof] config)."""

    def __init__(self, clock=time.monotonic) -> None:
        self._clock = clock
        self._lock = threading.Lock()
        self._recs: dict[str, FnRecord] = {}
        self.enabled = True
        # storm = storm_threshold compiles of ONE fn inside
        # storm_window_s (per-episode flag, watchdog-style)
        self.storm_window_s = 60.0
        self.storm_threshold = 5
        self.rounds_kept = 256
        self._rounds: deque = deque(maxlen=self.rounds_kept)
        self.transfer = {"h2d": {"bytes": 0, "seconds": 0.0, "count": 0},
                         "d2h": {"bytes": 0, "seconds": 0.0, "count": 0}}

    def configure(self, enabled: Optional[bool] = None,
                  storm_window_s: Optional[float] = None,
                  storm_threshold: Optional[int] = None,
                  rounds_kept: Optional[int] = None) -> None:
        if enabled is not None:
            self.enabled = enabled
        if storm_window_s is not None:
            self.storm_window_s = max(0.1, storm_window_s)
        if storm_threshold is not None:
            self.storm_threshold = max(2, int(storm_threshold))
        if rounds_kept is not None and rounds_kept != self.rounds_kept:
            self.rounds_kept = max(1, int(rounds_kept))
            with self._lock:
                self._rounds = deque(self._rounds,
                                     maxlen=self.rounds_kept)

    # ---- the jit seam ------------------------------------------------------

    def jit(self, fn: Optional[Callable] = None, *,
            name: Optional[str] = None, **jit_kwargs):
        """jax.jit with a ledger entry.  All three house forms work:

          @deviceprof.jit                       bare decorator
          @deviceprof.jit(static_argnames=...)  parameterized decorator
          deviceprof.jit(mapped, name="...")    direct wrap (the
                                                shard_map builders)
        """
        if fn is None:
            return lambda f: self.jit(f, name=name, **jit_kwargs)
        fn_name = name or getattr(fn, "__name__", None) or repr(fn)
        return ProfiledJit(self, fn, fn_name, jit_kwargs)

    def _record(self, name: str) -> FnRecord:
        with self._lock:
            rec = self._recs.get(name)
            if rec is None:
                rec = FnRecord(name)
                self._recs[name] = rec
            return rec

    def _profiled_call(self, rec: FnRecord, jitted, args, kwargs):
        t0 = time.perf_counter()
        out = jitted(*args, **kwargs)
        wall = time.perf_counter() - t0
        compiled = False
        try:
            # jit's OWN cache is the ground truth for "did this call
            # compile" — it keys on exactly what triggers a recompile
            size = jitted._cache_size()
            compiled = size > rec._cache_size
            rec._cache_size = size
        except Exception:  # noqa: BLE001 — fall back to our own keys
            key = _call_key(args, kwargs)
            compiled = key not in rec._keys
            rec._keys.add(key)
        if compiled:
            self._note_compile(rec, _call_key(args, kwargs), wall)
        else:
            with self._lock:
                rec.dispatches += 1
                rec.dispatch_seconds += wall
            _DISPATCHES.labels(fn=rec.name).inc()
            _DISPATCH_SECONDS.labels(fn=rec.name).observe(wall)
            trace_add("stage_device_dispatch_ms", wall * 1e3)
        return out

    def _note_compile(self, rec: FnRecord, key: tuple,
                      wall: float) -> None:
        now = self._clock()
        storm_fired = False
        churn_dim = None
        with self._lock:
            rec.compiles += 1
            rec.compile_seconds += wall
            rec.last_compile_s = wall
            # the churn ledger: which key dimension differed from the
            # PREVIOUS compile — a storm names the most frequent one
            if rec._prev_key is not None:
                prev, cur = dict(rec._prev_key), dict(key)
                for k in set(prev) | set(cur):
                    if prev.get(k) != cur.get(k):
                        rec._churn[k] = rec._churn.get(k, 0) + 1
            rec._prev_key = key
            rec.last_key = key
            w = rec._window
            w.append(now)
            while w and w[0] < now - self.storm_window_s:
                w.popleft()
            if len(w) >= self.storm_threshold:
                if not rec.storm_active:
                    rec.storm_active = True  # one episode, one flag
                    rec.storms += 1
                    storm_fired = True
                    churn_dim = (max(rec._churn, key=rec._churn.get)
                                 if rec._churn else
                                 "(keys identical — jit cache lost?)")
            elif rec.storm_active:
                rec.storm_active = False  # episode over; next is new
        _COMPILES.labels(fn=rec.name).inc()
        _COMPILE_SECONDS.labels(fn=rec.name).inc(wall)
        trace_add("stage_device_compile_ms", wall * 1e3)
        if storm_fired:
            _STORMS.labels(fn=rec.name).inc()
            slow_logger.warning(
                "[deviceprof] recompile storm: fn=%s %d compiles "
                "within %.0fs (threshold %d), churning key dimension: "
                "%s — capacity padding should keep shapes stable; a "
                "churning static arg means the dispatcher is minting "
                "program variants per call", rec.name,
                len(rec._window), self.storm_window_s,
                self.storm_threshold, churn_dim)

    # ---- the exec + transfer seams ----------------------------------------

    def block_until_ready(self, x, fn: str = "device"):
        """The exec-measurement seam: wall spent here is DEVICE
        execution (the dispatch already returned; this waits for the
        computation).  Returns `x` so call sites stay expressions."""
        import jax

        t0 = time.perf_counter()
        out = jax.block_until_ready(x)
        self.observe_exec(fn, time.perf_counter() - t0)
        return out

    def observe_exec(self, fn: str, seconds: float) -> None:
        """Charge already-measured device-execution wall (seams that
        time a dispatch+sync span themselves)."""
        if not self.enabled:
            return
        rec = self._record(fn)
        with self._lock:
            rec.execs += 1
            rec.exec_seconds += seconds
        _EXEC_SECONDS.labels(fn=fn).observe(seconds)
        trace_add("stage_device_exec_ms", seconds * 1e3)

    def device_put(self, x, *args, **kwargs):
        """jax.device_put with h2d accounting (bytes + enqueue wall)."""
        import jax

        t0 = time.perf_counter()
        out = jax.device_put(x, *args, **kwargs)
        self.charge_transfer("h2d", _nbytes(x),
                             seconds=time.perf_counter() - t0)
        return out

    def charge_transfer(self, direction: str, nbytes: int,
                        seconds: float = 0.0) -> None:
        """Account one host/device transfer.  `direction` is h2d|d2h;
        seams that only know bytes (a download already materialized as
        numpy) pass seconds=0 and the wall rides the enclosing stage."""
        if not self.enabled or nbytes <= 0:
            return
        with self._lock:
            t = self.transfer[direction]
            t["bytes"] += int(nbytes)
            t["seconds"] += seconds
            t["count"] += 1
        _TRANSFER_BYTES.labels(direction=direction).inc(int(nbytes))
        if seconds:
            _TRANSFER_SECONDS.labels(direction=direction).inc(seconds)
        trace_add(f"device_{direction}_bytes", float(nbytes))

    # ---- the mesh round timeline ------------------------------------------

    def record_round(self, kind: str, *, slots: int, capacity: int,
                     rows_per_shard: Optional[list] = None,
                     padding_rows: int = 0, upload_bytes: int = 0,
                     stack_hit: bool = False,
                     seconds: float = 0.0) -> None:
        """One mesh round's batching quality: how full the time axis
        was (`slots`/`capacity`), how many capacity-padding rows rode
        along dead, and how unevenly real rows landed per shard (max /
        mean — 1.0 is perfect balance)."""
        if not self.enabled:
            return
        rec = {
            "kind": kind,
            "slots": int(slots),
            "capacity": int(capacity),
            "fill_ratio": (round(slots / capacity, 4)
                           if capacity else None),
            "padding_rows": int(padding_rows),
            "upload_bytes": int(upload_bytes),
            "stack_hit": bool(stack_hit),
            "seconds": round(seconds, 6),
            "at": round(self._clock(), 3),
        }
        if rows_per_shard:
            rows = [int(r) for r in rows_per_shard]
            mean = sum(rows) / len(rows)
            rec["shard_rows"] = rows
            rec["row_imbalance"] = (round(max(rows) / mean, 4)
                                    if mean > 0 else None)
        with self._lock:
            self._rounds.append(rec)

    # ---- the /debug/device + /stats surface -------------------------------

    def records(self) -> list[FnRecord]:
        with self._lock:
            return list(self._recs.values())

    def snapshot(self) -> dict:
        """Full device-plane state (GET /debug/device): the compile-
        cache table, transfer totals, and the mesh round timeline
        (newest last)."""
        with self._lock:
            rounds = list(self._rounds)
            transfer = {d: dict(t) for d, t in self.transfer.items()}
        for t in transfer.values():
            t["seconds"] = round(t["seconds"], 6)
        fns = sorted((r.snapshot() for r in self.records()),
                     key=lambda d: d["fn"])
        return {
            "enabled": self.enabled,
            "storm": {"window_s": self.storm_window_s,
                      "threshold": self.storm_threshold},
            "fns": fns,
            "transfer": transfer,
            "rounds": rounds,
        }

    def summary(self) -> dict:
        """Compact rollup for /stats: totals plus any fn currently in
        a storm episode."""
        recs = self.records()
        with self._lock:
            transfer = {d: t["bytes"] for d, t in self.transfer.items()}
        return {
            "fns": len(recs),
            "compiles": sum(r.compiles for r in recs),
            "compile_seconds": round(
                sum(r.compile_seconds for r in recs), 3),
            "dispatches": sum(r.dispatches for r in recs),
            "storms": sorted(r.name for r in recs if r.storm_active),
            "transfer_bytes": transfer,
        }

    def clear(self) -> None:
        """Clear-on-close: reset every ledger entry and remove every
        labeled child so the families render empty — a closed engine
        serves no phantom device series.  Wrapper registrations (and
        jit's own caches) survive; only accounted state resets."""
        for rec in self.records():
            for fam in (_COMPILES, _COMPILE_SECONDS, _STORMS,
                        _DISPATCHES, _DISPATCH_SECONDS, _EXEC_SECONDS):
                fam.remove(fn=rec.name)
            with self._lock:
                rec.reset()
        with self._lock:
            self._rounds.clear()
            for t in self.transfer.values():
                t["bytes"], t["seconds"], t["count"] = 0, 0.0, 0
        for d in ("h2d", "d2h"):
            _TRANSFER_BYTES.remove(direction=d)
            _TRANSFER_SECONDS.remove(direction=d)


profiler = DeviceProfiler()

# module-level aliases: call sites read `deviceprof.jit(...)` /
# `deviceprof.device_put(...)` like the jax names they replace
jit = profiler.jit
block_until_ready = profiler.block_until_ready
observe_exec = profiler.observe_exec
device_put = profiler.device_put
charge_transfer = profiler.charge_transfer
record_round = profiler.record_round
