"""SeaHash — the 64-bit hash the reference specifies for metric/series ids
(ref: src/metric_engine/src/types.rs:40-42 uses seahash::hash; RFC
20240827: metric id = hash(name), TSID = hash(sorted labels)).

Pure-Python implementation of the published SeaHash algorithm (v4.x
reference semantics): four lanes seeded with the standard constants,
8-byte little-endian chunks diffused round-robin, finalized by diffusing
the lane XOR with the byte count.  The reference's metric engine never
persisted data (todo!() bodies), so there is no on-disk compatibility
surface — determinism and distribution are what matter.
"""

from __future__ import annotations

_MASK = (1 << 64) - 1
_K = 0x6EED_0E9D_A4D9_4A4F

_SEED_A = 0x16F1_1FE8_9B0D_677C
_SEED_B = 0xB480_A793_D8E6_C86C
_SEED_C = 0x6FE2_E5AA_F078_EBC9
_SEED_D = 0x14F9_94A4_C525_9381


def _diffuse(x: int) -> int:
    x = (x * _K) & _MASK
    x ^= (x >> 32) >> (x >> 60)
    return (x * _K) & _MASK


def hash64(buf: bytes) -> int:
    """SeaHash of `buf` with the default seed.

    Routed through the C++ kernel when the native library is ALREADY
    loaded (never triggers the synchronous build — a request-path hash
    must not block behind a compile; bulk ingest's tsids_of_keys pays
    the one-time build instead).  Golden-tested byte-identical to the
    Python spec twin below, which is also the fallback."""
    from horaedb_tpu import native

    if native.is_loaded():
        h = native.seahash64(buf)
        if h is not None:
            return h
    return _hash64_py(buf)


def _hash64_py(buf: bytes) -> int:
    """Pure-Python SeaHash (the spec; see module docstring)."""
    a, b, c, d = _SEED_A, _SEED_B, _SEED_C, _SEED_D
    n = len(buf)
    i = 0
    while n - i >= 32:
        a = _diffuse(a ^ int.from_bytes(buf[i:i + 8], "little"))
        b = _diffuse(b ^ int.from_bytes(buf[i + 8:i + 16], "little"))
        c = _diffuse(c ^ int.from_bytes(buf[i + 16:i + 24], "little"))
        d = _diffuse(d ^ int.from_bytes(buf[i + 24:i + 32], "little"))
        i += 32
    lanes = [a, b, c, d]
    lane = 0
    while i < n:
        chunk = buf[i:i + 8]
        lanes[lane] = _diffuse(lanes[lane] ^ int.from_bytes(chunk, "little"))
        lane += 1
        i += 8
    a, b, c, d = lanes
    return _diffuse(a ^ b ^ c ^ d ^ n)
