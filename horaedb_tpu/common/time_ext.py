"""Human-readable durations and the engine wall clock.

Mirrors the reference's `ReadableDuration` ("500ms"/"12h"-style values in
config files, units d/h/m/s/ms in descending order) and `now()` returning
milliseconds since epoch (ref: src/common/src/time_ext.rs:39-217).

Note: the reference's compaction picker mixes this millisecond clock with a
microsecond TTL (picker.rs:57) -- a unit bug SURVEY.md flags; we keep
everything in milliseconds.
"""

from __future__ import annotations

import re
import time

from horaedb_tpu.common.error import Error

_MS_PER_UNIT = {
    "d": 24 * 60 * 60 * 1000,
    "h": 60 * 60 * 1000,
    "m": 60 * 1000,
    "s": 1000,
    "ms": 1,
}

_TOKEN_RE = re.compile(r"(\d+(?:\.\d*)?)(d|h|ms|m|s)")


class ReadableDuration:
    """A duration parsed from / rendered to the "1h30m" config syntax."""

    __slots__ = ("millis",)

    def __init__(self, millis: int):
        if millis < 0:
            raise Error(f"duration must be non-negative, got {millis}")
        self.millis = int(millis)

    @classmethod
    def parse(cls, s: str) -> "ReadableDuration":
        text = s.strip().lower()
        if not text:
            raise Error("empty duration string")
        total = 0.0
        pos = 0
        last_unit_rank = -1
        units = list(_MS_PER_UNIT)
        for m in _TOKEN_RE.finditer(text):
            if m.start() != pos:
                raise Error(f"invalid duration string: {s!r}")
            value, unit = float(m.group(1)), m.group(2)
            rank = units.index(unit)
            if rank <= last_unit_rank:
                # units must appear at most once, in d h m s ms order
                raise Error(f"invalid unit order in duration: {s!r}")
            last_unit_rank = rank
            total += value * _MS_PER_UNIT[unit]
            pos = m.end()
        if pos != len(text):
            raise Error(f"invalid duration string: {s!r}")
        return cls(round(total))

    @classmethod
    def from_millis(cls, millis: int) -> "ReadableDuration":
        return cls(millis)

    @classmethod
    def from_secs(cls, secs: float) -> "ReadableDuration":
        return cls(round(secs * 1000))

    @property
    def seconds(self) -> float:
        return self.millis / 1000.0

    def __eq__(self, other: object) -> bool:
        return isinstance(other, ReadableDuration) and other.millis == self.millis

    def __hash__(self) -> int:
        return hash(self.millis)

    def __repr__(self) -> str:
        return f"ReadableDuration({self})"

    def __str__(self) -> str:
        if self.millis == 0:
            return "0s"
        rem = self.millis
        parts = []
        for unit, ms in _MS_PER_UNIT.items():
            n, rem = divmod(rem, ms)
            if n:
                parts.append(f"{n}{unit}")
        return "".join(parts)


def now_ms() -> int:
    """Wall clock in milliseconds since epoch (ref: time_ext.rs:212-217)."""
    return time.time_ns() // 1_000_000
