"""Named worker pools — the reference's dedicated runtimes.

The reference serves queries on the main tokio runtime and pins
manifest folds and SST compaction onto separate runtimes so a long
compaction cannot starve serving (ref: src/storage/src/storage.rs:91-104,
src/server/src/main.rs:104-109 builds them from
threads.manifest_thread_num / threads.sst_thread_num).

The asyncio analogue: the event loop stays an I/O scheduler only, and
every CPU-heavy step — parquet encode/decode, host merge, numpy window
prep, device dispatch + blocking syncs — runs on one of these pools via
run_in_executor.  Pools:

  sst      — serving reads/writes (parquet decode/encode, merge prep)
  compact  — compaction rewrites (so they queue behind each other, not
             in front of serving work)
  manifest — manifest codec/folds
"""

from __future__ import annotations

import asyncio
import contextvars
import functools
from concurrent.futures import ThreadPoolExecutor
from typing import Callable


class Runtimes:
    """Owner of the named pools.  `close()` only shuts down pools this
    instance created (a sharing parent keeps ownership)."""

    def __init__(self, sst_threads: int = 4, compact_threads: int = 2,
                 manifest_threads: int = 1):
        self._pools = {
            "sst": ThreadPoolExecutor(sst_threads,
                                      thread_name_prefix="horaedb-sst"),
            "compact": ThreadPoolExecutor(
                compact_threads, thread_name_prefix="horaedb-compact"),
            "manifest": ThreadPoolExecutor(
                manifest_threads, thread_name_prefix="horaedb-manifest"),
        }

    async def run(self, pool: str, fn: Callable, *args, **kwargs):
        """Run fn(*args, **kwargs) on the named pool; await the result.
        The caller's contextvars context rides along (run_in_executor,
        unlike asyncio.to_thread, does not copy it) so request-scoped
        state — the ambient trace, deadline — stays visible to stage
        attribution inside pool work."""
        loop = asyncio.get_running_loop()
        ctx = contextvars.copy_context()
        return await loop.run_in_executor(
            self._pools[pool],
            functools.partial(ctx.run,
                              functools.partial(fn, *args, **kwargs)))

    def close(self) -> None:
        # wait=True is load-bearing: shutdown(wait=False) leaves an
        # in-flight parquet encode/merge running on the worker thread
        # AFTER the owner tears down the engine — the job then races
        # object teardown and corrupts the heap (observed as later
        # segfaults/aborts inside pyarrow).  Queued-but-unstarted jobs
        # are cancelled; the bounded in-flight ones finish first.
        for pool in self._pools.values():
            pool.shutdown(wait=True, cancel_futures=True)


def from_config(threads, sst_override: int = 0) -> Runtimes:
    """Build pools from a ThreadsConfig (storage.config).
    `sst_override` > 0 widens/narrows the serving decode pool — the
    [scan] decode_workers knob for cold-path tuning."""
    return Runtimes(sst_threads=sst_override or threads.sst_thread_num,
                    compact_threads=threads.compact_thread_num,
                    manifest_threads=threads.manifest_thread_num)
