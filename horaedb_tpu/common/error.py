"""Framework-wide error type.

The reference uses a single-variant error enum wrapping an arbitrary cause
plus an `ensure!` guard macro (ref: src/common/src/error.rs:18-28,
src/storage/src/macros.rs:35-52).  Python's exception chaining gives us the
anyhow-style context chain for free; `ensure` is the guard helper.
"""

from __future__ import annotations


class Error(Exception):
    """Single framework error; context is carried via `raise ... from e`."""

    @classmethod
    def context(cls, msg: str, cause: BaseException) -> "Error":
        err = cls(msg)
        err.__cause__ = cause
        return err


def ensure(cond: object, msg: str) -> None:
    """Guard helper mirroring the reference's `ensure!` macro."""
    if not cond:
        raise Error(msg)
