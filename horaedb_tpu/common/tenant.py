"""Per-tenant isolation primitives (docs/robustness.md, tenant
isolation failure domains).

A tenant identity enters at HTTP ingress (`X-Tenant` header; absent ->
the shared "default" tenant) and rides the same ambient-contextvar
plumbing the request `Deadline` uses: layers that never knew about
tenants need no signature changes, and worker-pool jobs dispatched via
`runtimes.run` / `asyncio.to_thread` see the tenant too (contextvars
are copied onto the executor).

Resource governance lives at the layer that owns the resource (the
Taurus NDP framing, PAPERS.md):

  * admission owns CONCURRENCY — weighted-fair queueing over per-tenant
    queues in the server (`server/main.py`, FairAdmissionController),
    driven by this module's `TenantLimits.weight / max_in_flight /
    max_queued`;
  * the scan path owns BYTES — `charge_scan_bytes()` charges the
    ambient tenant's scan token bucket at the read-stage attribution
    points (`storage/read.py`), and the deadline machinery's
    cooperative `checkpoint()` calls (storage/read.py,
    storage/pipeline.py) observe a bucket in deficit via the
    checkpoint hook registered here -> `QuotaExceeded` -> HTTP 429
    with a quota error body, never a silent slow-down;
  * the WAL owns INGEST RATE — `Tenant.admit_wal()` is consulted in
    `wal/ingest.py` ahead of the group-commit append, so a flooding
    writer is rejected before it costs an fsync.

Buckets are classic token buckets (rate + burst, monotonic clock,
thread-safe — charges arrive from pool threads).  A breach always
carries a `retry_after_s` derived from the actual deficit, so backoff
guidance tracks how far over budget the tenant is.
"""

from __future__ import annotations

import contextvars
import re
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

from horaedb_tpu.common import deadline as deadline_mod
from horaedb_tpu.common.error import Error, ensure
from horaedb_tpu.common.size_ext import ReadableSize
from horaedb_tpu.common.time_ext import ReadableDuration
from horaedb_tpu.utils.metrics import registry

DEFAULT_TENANT = "default"

_NAME_RE = re.compile(r"^[A-Za-z0-9._-]{1,64}$")

# per-tenant resource accounting; children are keyed tenant= (and
# resource= for the rejection counter) and removed when a tenant's
# config is dropped at reload (TenantRegistry.reload) so /metrics
# never serves phantom tenants
_SCAN_BYTES = registry.counter(
    "tenant_scan_bytes_total",
    "bytes entering the scan read stages, charged to the requesting "
    "tenant's scan budget")
_WAL_BYTES = registry.counter(
    "tenant_wal_bytes_total",
    "bytes admitted into the WAL group commit per tenant")
_QUOTA_REJECTIONS = registry.counter(
    "tenant_quota_rejections_total",
    "requests rejected with 429 for a per-tenant resource quota "
    "breach (resource=scan_bytes|wal_rate)")
_QUERY_SECONDS = registry.histogram(
    "tenant_query_seconds",
    "governed-endpoint request latency per tenant (server-side)")


class QuotaExceeded(Error):
    """A per-tenant resource quota was breached.  The server maps this
    to HTTP 429 with a quota error body and a Retry-After derived from
    the bucket's actual deficit (never a constant)."""

    def __init__(self, tenant: str, resource: str, retry_after_s: float,
                 detail: str = ""):
        self.tenant = tenant
        self.resource = resource
        self.retry_after_s = max(0.0, retry_after_s)
        msg = (f"tenant {tenant!r} over its {resource} quota"
               + (f": {detail}" if detail else ""))
        super().__init__(msg)


class TokenBucket:
    """rate/burst token bucket on the monotonic clock.  Thread-safe:
    scan-byte charges arrive from worker-pool threads while the event
    loop checks the level at checkpoints."""

    def __init__(self, rate_per_s: float, burst: float,
                 clock=time.monotonic):
        ensure(rate_per_s > 0, "token bucket rate must be positive")
        self.rate = float(rate_per_s)
        self.burst = max(float(burst), 1.0)
        self._clock = clock
        self._level = self.burst
        self._last = clock()
        self._lock = threading.Lock()

    def _refill_locked(self) -> None:
        now = self._clock()
        dt = now - self._last
        if dt > 0:
            self._level = min(self.burst, self._level + dt * self.rate)
            self._last = now

    @property
    def level(self) -> float:
        with self._lock:
            self._refill_locked()
            return self._level

    def admit(self, cost: float) -> bool:
        """Take `cost` tokens if affordable (pre-pay semantics: the WAL
        path).  A cost larger than the whole burst is admitted only
        against a FULL bucket (leaving it in deficit) — otherwise a
        big batch could never be admitted at all."""
        with self._lock:
            self._refill_locked()
            need = min(cost, self.burst)
            if self._level < need:
                return False
            self._level -= cost
            return True

    def charge(self, cost: float) -> None:
        """Deduct unconditionally, possibly into deficit (post-pay
        semantics: scan bytes are charged after the read happened; the
        deficit is observed at the next cooperative checkpoint)."""
        with self._lock:
            self._refill_locked()
            self._level -= cost

    @property
    def in_deficit(self) -> bool:
        return self.level < 0.0

    def delay_until(self, target: float = 0.0) -> float:
        """Seconds until the level refills to `target` (0 = out of
        deficit) — the Retry-After hint for a breach."""
        lvl = self.level
        if lvl >= target:
            return 0.0
        return (target - lvl) / self.rate


@dataclass
class TenantLimits:
    """One tenant's isolation envelope ([tenants.default] /
    [tenants.tenant.<name>]; unset per-tenant fields inherit from the
    default).  Zero means "unlimited / global bound only" for every
    field except weight and max_queued."""

    # weighted-fair admission share (stride scheduling): every grant
    # advances the tenant's virtual pass by 1/weight and a freed slot
    # goes to the eligible tenant with the lowest pass, so contending
    # tenants receive slots in proportion to their weights over time
    weight: float = 1.0
    # hard cap on this tenant's concurrently EXECUTING queries
    # (0 = bounded only by [admission] max_concurrent_queries)
    max_in_flight: int = 0
    # this tenant's own admission wait queue; arrivals beyond it are
    # shed with a 429 scoped to the tenant
    max_queued: int = 64
    # operator-side deadline CAP for this tenant's requests (0 =
    # inherit the [admission] per-endpoint defaults): a no-SLO batch
    # class capped at, say, 1s cannot hold server time — CPU, pool
    # slots, the GIL — for long stretches even when its queries are
    # admitted, which bounds the collateral its work inflicts on
    # latency-SLO tenants sharing the host
    max_query_time: ReadableDuration = field(
        default_factory=lambda: ReadableDuration.from_millis(0))
    # scan-byte budget: a token bucket charged at the read-stage
    # attribution points (0 = unlimited)
    scan_bytes_per_s: ReadableSize = field(
        default_factory=lambda: ReadableSize(0))
    scan_burst_bytes: ReadableSize = field(
        default_factory=lambda: ReadableSize(0))  # 0 -> 2s of rate
    # WAL ingest-rate budget, consulted ahead of group commit
    # (0 = unlimited)
    wal_bytes_per_s: ReadableSize = field(
        default_factory=lambda: ReadableSize(0))
    wal_burst_bytes: ReadableSize = field(
        default_factory=lambda: ReadableSize(0))  # 0 -> 2s of rate


@dataclass
class TenantsConfig:
    """[tenants]: per-tenant isolation (weighted-fair admission +
    resource quotas).  Disabled reproduces the pre-tenant global
    admission behavior exactly — the server keeps the single FIFO
    controller and no quota machinery binds."""

    enabled: bool = False
    # auto_tenants = true mints unknown X-Tenant names their OWN
    # runtime tenant with the default limits (bounded by
    # max_auto_tenants).  X-Tenant is UNAUTHENTICATED, so each fresh
    # name is a fresh fair share and a fresh set of quota buckets — a
    # client rotating names multiplies its share until the cap.  The
    # default is therefore OFF: unknown names share the single
    # "default" tenant (one weight, one bucket set — rotation gains
    # nothing).  Turn it on only where the ingress layer has already
    # authenticated the tenant header.
    auto_tenants: bool = False
    max_auto_tenants: int = 64
    default: TenantLimits = field(default_factory=TenantLimits)
    tenants: dict = field(default_factory=dict)  # name -> TenantLimits


_LIMIT_KEYS = ("weight", "max_in_flight", "max_queued",
               "max_query_time",
               "scan_bytes_per_s", "scan_burst_bytes",
               "wal_bytes_per_s", "wal_burst_bytes")
_SIZE_KEYS = {"scan_bytes_per_s", "scan_burst_bytes",
              "wal_bytes_per_s", "wal_burst_bytes"}


def _limits_from_dict(data: dict, base: TenantLimits,
                      where: str) -> TenantLimits:
    ensure(isinstance(data, dict), f"{where} expects a config table")
    unknown = set(data) - set(_LIMIT_KEYS)
    ensure(not unknown,
           f"unknown keys for {where}: {sorted(unknown)}")
    kwargs = {k: getattr(base, k) for k in _LIMIT_KEYS}
    for key, value in data.items():
        if key == "max_query_time":
            if not isinstance(value, ReadableDuration):
                ensure(isinstance(value, str),
                       f'{where}.max_query_time expects a duration '
                       'string like "1s"')
                value = ReadableDuration.parse(value)
            kwargs[key] = value
        elif key in _SIZE_KEYS:
            if not isinstance(value, ReadableSize):
                ensure(isinstance(value, (str, int)),
                       f'{where}.{key} expects a size like "64MiB"')
                value = (ReadableSize(value) if isinstance(value, int)
                         else ReadableSize.parse(value))
            kwargs[key] = value
        elif key == "weight":
            ensure(isinstance(value, (int, float))
                   and not isinstance(value, bool) and value > 0,
                   f"{where}.weight must be a positive number")
            kwargs[key] = float(value)
        else:
            ensure(isinstance(value, int) and not isinstance(value, bool)
                   and value >= 0,
                   f"{where}.{key} must be a non-negative integer")
            kwargs[key] = value
    return TenantLimits(**kwargs)


def tenants_from_dict(data: dict) -> TenantsConfig:
    """[tenants] TOML table -> TenantsConfig.  Per-tenant tables live
    under [tenants.tenant.<name>] and inherit unset fields from
    [tenants.default]."""
    ensure(isinstance(data, dict), "[tenants] expects a config table")
    known = {"enabled", "auto_tenants", "max_auto_tenants", "default",
             "tenant"}
    unknown = set(data) - known
    ensure(not unknown, f"unknown [tenants] keys: {sorted(unknown)}")
    cfg = TenantsConfig()
    if "enabled" in data:
        ensure(isinstance(data["enabled"], bool),
               "[tenants] enabled must be a boolean")
        cfg.enabled = data["enabled"]
    if "auto_tenants" in data:
        ensure(isinstance(data["auto_tenants"], bool),
               "[tenants] auto_tenants must be a boolean")
        cfg.auto_tenants = data["auto_tenants"]
    if "max_auto_tenants" in data:
        v = data["max_auto_tenants"]
        ensure(isinstance(v, int) and not isinstance(v, bool) and v >= 0,
               "[tenants] max_auto_tenants must be a non-negative int")
        cfg.max_auto_tenants = v
    if "default" in data:
        cfg.default = _limits_from_dict(data["default"], TenantLimits(),
                                        "[tenants.default]")
    for name, table in (data.get("tenant") or {}).items():
        ensure(_NAME_RE.match(name) is not None,
               f"bad tenant name {name!r} (want [A-Za-z0-9._-]{{1,64}})")
        ensure(name != DEFAULT_TENANT,
               "configure the default tenant via [tenants.default], "
               "not [tenants.tenant.default]")
        cfg.tenants[name] = _limits_from_dict(
            table, cfg.default, f"[tenants.tenant.{name}]")
    return cfg


class Tenant:
    """Runtime tenant state: quota buckets + pre-bound metric children.
    One instance per distinct tenant name; admission-queue state lives
    in the server's FairAdmissionController."""

    def __init__(self, name: str, limits: TenantLimits,
                 auto: bool = False, clock=time.monotonic):
        self.name = name
        self.limits = limits
        self.auto = auto
        scan_rate = limits.scan_bytes_per_s.bytes
        self.scan_bucket = (TokenBucket(
            scan_rate, limits.scan_burst_bytes.bytes or 2 * scan_rate,
            clock=clock) if scan_rate else None)
        wal_rate = limits.wal_bytes_per_s.bytes
        self.wal_bucket = (TokenBucket(
            wal_rate, limits.wal_burst_bytes.bytes or 2 * wal_rate,
            clock=clock) if wal_rate else None)
        self._scan_bytes = _SCAN_BYTES.labels(tenant=name)
        self._wal_bytes = _WAL_BYTES.labels(tenant=name)
        self.query_seconds = _QUERY_SECONDS.labels(tenant=name)

    def charge_scan_bytes(self, nbytes: int) -> None:
        if nbytes <= 0:
            return
        self._scan_bytes.inc(nbytes)
        if self.scan_bucket is not None:
            self.scan_bucket.charge(nbytes)

    def check_scan_budget(self) -> None:
        """Raise QuotaExceeded when the scan bucket is in deficit —
        called from the deadline machinery's cooperative checkpoints,
        so a breach surfaces within one checkpoint interval."""
        b = self.scan_bucket
        if b is not None and b.in_deficit:
            raise QuotaExceeded(self.name, "scan_bytes",
                                b.delay_until(0.0),
                                "scan-byte budget exhausted")

    def admit_wal(self, nbytes: int) -> None:
        """Admit `nbytes` of WAL ingest or raise QuotaExceeded — the
        check runs AHEAD of the group-commit append, so a rejected
        write never costs an fsync."""
        b = self.wal_bucket
        if b is not None and not b.admit(nbytes):
            raise QuotaExceeded(
                self.name, "wal_rate",
                b.delay_until(min(nbytes, b.burst)),
                f"ingest of {nbytes} bytes exceeds the WAL rate budget")
        self._wal_bytes.inc(nbytes)

    def quota_rejected(self, resource: str) -> None:
        """Server-side accounting hook: exactly one inc per 429
        response (the raise sites don't count — a breach can be
        observed at several checkpoints before the query dies)."""
        _QUOTA_REJECTIONS.labels(tenant=self.name,
                                 resource=resource).inc()

    def remove_metrics(self) -> None:
        """Drop this tenant's children from every tenant-labeled
        family so a removed tenant stops rendering on /metrics (same
        discipline as the heartbeat-age zeroing: gone means gone)."""
        for fam in (_SCAN_BYTES, _WAL_BYTES, _QUERY_SECONDS):
            fam.remove(tenant=self.name)
        for resource in ("scan_bytes", "wal_rate"):
            _QUOTA_REJECTIONS.remove(tenant=self.name, resource=resource)
        # the server's admission families label by tenant too
        for name in ("server_queries_shed_total",
                     "server_queries_queue_timeout_total",
                     "server_requests_timed_out_total",
                     "server_active_queries", "server_queued_queries"):
            fam = registry.family(name)
            if fam is not None:
                fam.remove(tenant=self.name)

    def stats(self) -> dict:
        out = {
            "weight": self.limits.weight,
            "max_in_flight": self.limits.max_in_flight,
            "max_queued": self.limits.max_queued,
            "auto": self.auto,
            "scan_bytes": self._scan_bytes.value,
            "wal_bytes": self._wal_bytes.value,
            "query_p50_s": self.query_seconds.quantile(0.5),
            "query_p99_s": self.query_seconds.quantile(0.99),
            "queries": self.query_seconds.count,
        }
        if self.scan_bucket is not None:
            out["scan_bucket_level"] = round(self.scan_bucket.level)
        if self.wal_bucket is not None:
            out["wal_bucket_level"] = round(self.wal_bucket.level)
        return out


class TenantRegistry:
    """name -> Tenant for one server, built from [tenants].  Unknown
    names become bounded auto-tenants with the default limits; at
    reload, tenants dropped from the config have their metric children
    removed so /metrics never serves phantom tenants."""

    def __init__(self, config: TenantsConfig, clock=time.monotonic):
        self._clock = clock
        self._lock = threading.Lock()
        self.configure(config)

    def configure(self, config: TenantsConfig) -> list:
        """(Re)build from `config`; returns the removed tenant names.
        Bucket levels reset — a reload is a policy change, not an
        accounting continuation."""
        with self._lock:
            old = getattr(self, "_tenants", {})
            self.config = config
            self._tenants = {
                DEFAULT_TENANT: Tenant(DEFAULT_TENANT, config.default,
                                       clock=self._clock)}
            for name, limits in config.tenants.items():
                self._tenants[name] = Tenant(name, limits,
                                             clock=self._clock)
            removed = [n for n in old if n not in self._tenants]
            for name in removed:
                old[name].remove_metrics()
            return removed

    @property
    def enabled(self) -> bool:
        return self.config.enabled

    def resolve(self, name: Optional[str]) -> Tenant:
        """The Tenant for an X-Tenant header value (None/"" -> the
        default tenant).  Raises Error on a malformed name — the
        server answers 400 before anything is charged."""
        if not name:
            name = DEFAULT_TENANT
        if _NAME_RE.match(name) is None:
            raise Error(f"bad X-Tenant {name!r} "
                        "(want [A-Za-z0-9._-]{1,64})")
        with self._lock:
            t = self._tenants.get(name)
            if t is not None:
                return t
            if (not self.config.auto_tenants
                    or len(self._tenants) - 1 - len(self.config.tenants)
                    >= self.config.max_auto_tenants):
                return self._tenants[DEFAULT_TENANT]
            t = Tenant(name, self.config.default, auto=True,
                       clock=self._clock)
            self._tenants[name] = t
            return t

    def known(self) -> list:
        with self._lock:
            return list(self._tenants.values())

    def stats(self) -> dict:
        return {t.name: t.stats() for t in self.known()}


_CURRENT: contextvars.ContextVar[Optional[Tenant]] = \
    contextvars.ContextVar("horaedb_tenant", default=None)


def current_tenant() -> Optional[Tenant]:
    """The ambient tenant, or None outside any governed request scope
    (background loops: flusher, compaction, meta-ingest — ungoverned
    by design; their resource use is the system's own)."""
    return _CURRENT.get()


class tenant_scope:
    """Bind a tenant as ambient for the `with` body (sync or async)."""

    __slots__ = ("tenant", "_token")

    def __init__(self, tenant: Optional[Tenant]):
        self.tenant = tenant
        self._token = None

    def __enter__(self) -> Optional[Tenant]:
        self._token = _CURRENT.set(self.tenant)
        return self.tenant

    def __exit__(self, *exc) -> None:
        _CURRENT.reset(self._token)


def charge_scan_bytes(nbytes: int) -> None:
    """Charge the ambient tenant's scan budget (no-op outside a tenant
    scope).  Called at the read-stage byte-attribution points — pool
    threads included, since runtimes.run copies contextvars."""
    t = _CURRENT.get()
    if t is not None:
        t.charge_scan_bytes(nbytes)


def _budget_checkpoint() -> None:
    """Deadline-checkpoint hook: a scan bucket in deficit surfaces at
    the same cooperative cancellation points an expired deadline does
    (storage/read.py, storage/pipeline.py)."""
    t = _CURRENT.get()
    if t is not None:
        t.check_scan_budget()


deadline_mod.add_checkpoint_hook(_budget_checkpoint)
