"""Background-loop registry + watchdog: the maintenance plane's
liveness surface (docs/observability.md, background plane).

Every long-running background loop in the process — compaction picker/
executor, orphan scrubber, manifest merger, WAL group committer,
memtable flusher, rollup maintenance, the cluster health monitor, the
meta-ingest scraper — is spawned through `loops.spawn(...)` instead of
a bare `asyncio.create_task` (tools/lint.py enforces this under
horaedb_tpu/), which registers a `LoopHandle` the loop heartbeats once
per iteration.  The registry then answers three questions nothing else
can:

  is it alive?      the task exists and has not finished
  is it moving?     heartbeat age vs. the loop's stall threshold
  is it healthy?    last success, consecutive errors, last error text

A watchdog loop (auto-started on the first spawn; `[watchdog]` config)
sweeps the registry: a non-idle loop whose heartbeat age exceeds its
stall threshold is flagged — `loop_stalled_total{loop=}` fires once per
stall episode, a `[watchdog]` line hits the slow log — and the flag
clears when beats resume.  `GET /debug/tasks` serves the full snapshot
(plus per-loop backlog hints: WAL backlog bytes, dirty rollup segments,
pending compaction tasks) and `/stats` carries the compact summary, so
degraded maintenance is visible before it becomes a query-latency
incident.

Heartbeat discipline for loop authors:

  hb.beat()   at the top of every iteration ("I woke up and I'm
              responsive"); loops that park on a TIMED wait (wait_for
              with their period as timeout) beat at least once per
              period by construction
  hb.idle()   before parking on an UNBOUNDED wait (queue.get, an
              un-timed Event) — absence of beats while idle is healthy,
              so idle loops are exempt from stall checks until the next
              beat
  hb.ok() / hb.error(exc)   the iteration's outcome; errors feed
              `loop_errors_total{loop=}` and the /debug/tasks error
              surface instead of vanishing into an `except: pass`

Loops doing legitimately long single iterations (a compaction rewrite, a
whole-table rollup backfill) pass an explicit `stall_threshold_s`
sized to their worst case — the watchdog flags *wedged*, not *busy*.

The registry is process-global (like utils.metrics.registry and
utils.tracing.recorder).  Handles deregister automatically when their
task finishes — `cancel_and_wait` on a stalled loop leaves no phantom
"stalled" entry behind — and handles whose event loop died without the
task completing (a test's asyncio.run that never closed cleanly) are
pruned by the watchdog sweep.
"""

from __future__ import annotations

import asyncio
import logging
import threading
import time
from typing import Callable, Optional

from horaedb_tpu.utils.metrics import registry

logger = logging.getLogger(__name__)
# stall flags land next to slow queries: both are "the system is not
# keeping up" events an operator greps one stream for
slow_logger = logging.getLogger("horaedb_tpu.trace.slow")

# the `loop` label is the handle's KIND (the stable prefix before ":"),
# not the full instance name — per-table instance names embed temp
# paths and would be unbounded label values across a process's life
_STALLS = registry.counter(
    "loop_stalled_total",
    "background-loop stall episodes flagged by the watchdog, by loop "
    "kind")
_ERRORS = registry.counter(
    "loop_errors_total",
    "background-loop iteration errors, by loop kind")
_REGISTERED = registry.gauge(
    "loops_registered", "background loops currently registered")
_STALLED_NOW = registry.gauge(
    "loops_stalled", "background loops currently flagged as stalled")
_HB_AGE = registry.gauge(
    "loop_heartbeat_age_seconds",
    "oldest heartbeat age among live non-idle loops of a kind "
    "(updated each watchdog round)")


class LoopHandle:
    """One background loop's liveness record.  Mutated from the loop's
    own event loop; read from server handlers and the watchdog — every
    field is a scalar write, guarded by the registry lock only where a
    check-and-set matters (stall transitions)."""

    __slots__ = ("name", "kind", "owner", "period_s", "stall_threshold_s",
                 "backlog", "task", "created_at", "last_beat", "idle_flag",
                 "last_success", "iterations", "consecutive_errors",
                 "last_error", "last_error_at", "stalled", "_clock")

    def __init__(self, name: str, kind: str, owner: str,
                 period_s: Optional[float],
                 stall_threshold_s: Optional[float],
                 backlog: Optional[Callable[[], dict]],
                 clock=time.monotonic):
        self.name = name
        self.kind = kind
        self.owner = owner
        self.period_s = period_s
        self.stall_threshold_s = stall_threshold_s
        self.backlog = backlog
        self.task: Optional[asyncio.Task] = None
        self._clock = clock
        self.created_at = clock()
        # until the first beat, the spawn time IS the heartbeat — a
        # loop that never reaches its first iteration must still stall
        self.last_beat = self.created_at
        self.idle_flag = False
        self.last_success: Optional[float] = None
        self.iterations = 0
        self.consecutive_errors = 0
        self.last_error: Optional[str] = None
        self.last_error_at: Optional[float] = None
        self.stalled = False

    # ---- the loop-author surface ------------------------------------------

    def beat(self) -> None:
        """Heartbeat: call at the top of every iteration."""
        self.last_beat = self._clock()
        self.idle_flag = False
        self.iterations += 1

    def idle(self) -> None:
        """About to park on an unbounded wait — exempt from stall
        checks until the next beat."""
        self.last_beat = self._clock()
        self.idle_flag = True

    def ok(self) -> None:
        self.last_success = self._clock()
        self.consecutive_errors = 0

    def error(self, exc: BaseException) -> None:
        self.consecutive_errors += 1
        self.last_error = f"{type(exc).__name__}: {exc}"
        self.last_error_at = self._clock()
        _ERRORS.labels(loop=self.kind).inc()

    # ---- introspection ----------------------------------------------------

    def alive(self) -> bool:
        return self.task is not None and not self.task.done()

    def dead(self) -> bool:
        """Finished, or stranded on a closed event loop (a test's
        asyncio.run that ended without this task completing)."""
        if self.task is None:
            return False
        if self.task.done():
            return True
        try:
            return self.task.get_loop().is_closed()
        except RuntimeError:
            return True


class LoopRegistry:
    """Process-global registry + watchdog ([watchdog] config)."""

    def __init__(self, clock=time.monotonic) -> None:
        self._clock = clock
        self._handles: dict[str, LoopHandle] = {}
        self._lock = threading.Lock()
        self._watchdog_task: Optional[asyncio.Task] = None
        # kinds whose heartbeat-age gauge was written by a past sweep:
        # a kind that goes idle or deregisters must be zeroed, not left
        # serving its last (possibly huge) age forever
        self._hb_kinds: set[str] = set()
        self.enabled = True
        self.interval_s = 1.0
        self.stall_factor = 4.0
        self.min_stall_s = 5.0

    def configure(self, enabled: Optional[bool] = None,
                  interval_s: Optional[float] = None,
                  stall_factor: Optional[float] = None,
                  min_stall_s: Optional[float] = None) -> None:
        if enabled is not None:
            self.enabled = enabled
        if interval_s is not None:
            self.interval_s = max(0.01, interval_s)
        if stall_factor is not None:
            self.stall_factor = max(1.0, stall_factor)
        if min_stall_s is not None:
            self.min_stall_s = max(0.0, min_stall_s)

    # ---- registration -----------------------------------------------------

    def register(self, name: str, kind: Optional[str] = None,
                 period_s: Optional[float] = None, owner: str = "",
                 stall_threshold_s: Optional[float] = None,
                 backlog: Optional[Callable[[], dict]] = None
                 ) -> LoopHandle:
        """Register a loop by UNIQUE name (a live duplicate gets a #n
        suffix — two engines over the same root must not share one
        heartbeat).  `kind` is the stable metric label; it defaults to
        the name's prefix before ":"."""
        if kind is None:
            kind = name.split(":", 1)[0].split("#", 1)[0]
        with self._lock:
            base, n = name, 2
            while name in self._handles and not self._handles[name].dead():
                name = f"{base}#{n}"
                n += 1
            handle = LoopHandle(name, kind, owner, period_s,
                                stall_threshold_s, backlog,
                                clock=self._clock)
            self._handles[name] = handle
            _REGISTERED.set(len(self._handles))
        return handle

    def deregister(self, handle: LoopHandle) -> None:
        with self._lock:
            if self._handles.get(handle.name) is handle:
                del self._handles[handle.name]
            _REGISTERED.set(len(self._handles))
            if handle.stalled:
                handle.stalled = False
            _STALLED_NOW.set(sum(1 for h in self._handles.values()
                                 if h.stalled))

    def get(self, name: str) -> Optional[LoopHandle]:
        with self._lock:
            return self._handles.get(name)

    def handles(self, kind: Optional[str] = None) -> list[LoopHandle]:
        with self._lock:
            hs = list(self._handles.values())
        return hs if kind is None else [h for h in hs if h.kind == kind]

    # ---- spawn ------------------------------------------------------------

    def spawn(self, factory: Callable[[LoopHandle], "object"], *,
              name: str, kind: Optional[str] = None,
              period_s: Optional[float] = None, owner: str = "",
              stall_threshold_s: Optional[float] = None,
              backlog: Optional[Callable[[], dict]] = None,
              _watch: bool = True) -> asyncio.Task:
        """THE way to start a background loop (tools/lint.py rejects
        bare create_task of loop coroutines under horaedb_tpu/):
        registers a handle, creates the task, and deregisters when the
        task finishes — however it finishes, including a
        `cancel_and_wait` that had to re-deliver its cancel."""
        handle = self.register(name, kind=kind, period_s=period_s,
                               owner=owner,
                               stall_threshold_s=stall_threshold_s,
                               backlog=backlog)
        task = asyncio.create_task(factory(handle), name=handle.name)
        handle.task = task
        task.add_done_callback(
            lambda _t, h=handle: self.deregister(h))
        if _watch:
            self.ensure_watchdog()
        return task

    # ---- watchdog ---------------------------------------------------------

    def ensure_watchdog(self) -> None:
        """Lazy-start the watchdog on the CURRENT event loop.  A task
        left over from a previous (closed) loop is abandoned — its
        handle prunes on the next sweep — and replaced."""
        try:
            running = asyncio.get_running_loop()
        except RuntimeError:
            return
        t = self._watchdog_task
        if t is not None and not t.done():
            try:
                if t.get_loop() is running:
                    return
                if not t.get_loop().is_closed():
                    # a live watchdog on another loop still sweeps the
                    # shared registry; don't double up
                    return
            except RuntimeError:
                pass
        self._watchdog_task = self.spawn(
            self._watchdog_loop, name="watchdog",
            period_s=self.interval_s, owner="loops", _watch=False)

    async def _watchdog_loop(self, hb: LoopHandle) -> None:
        while True:
            hb.beat()
            try:
                if self.enabled:
                    self.check_once()
                hb.ok()
            except Exception as exc:  # noqa: BLE001 — watch next round
                hb.error(exc)
                logger.exception("watchdog round failed")
            await asyncio.sleep(self.interval_s)

    def resolved_threshold(self, h: LoopHandle) -> float:
        """Effective stall threshold.  A declared threshold is a FLOOR
        (sized to the loop's worst-case iteration), not an absolute:
        it still scales with the loop's configured period, so an
        operator who legally sets a 10-minute flush_interval doesn't
        turn the flusher's quiet waits into stall flags."""
        scaled = self.stall_factor * (h.period_s or 0.0)
        if h.stall_threshold_s is not None:
            return max(h.stall_threshold_s, scaled)
        return max(self.min_stall_s, scaled)

    def check_once(self, now: Optional[float] = None) -> list[str]:
        """One watchdog sweep (callable directly from tests/ops): prune
        dead handles, flag stalls, clear recoveries.  Returns the names
        flagged THIS sweep."""
        now = self._clock() if now is None else now
        fired: list[str] = []
        ages: dict[str, float] = {}
        with self._lock:
            handles = list(self._handles.values())
        for h in handles:
            if h.dead():
                self.deregister(h)
                continue
            age = now - h.last_beat
            if not h.idle_flag:
                ages[h.kind] = max(ages.get(h.kind, 0.0), age)
            thr = self.resolved_threshold(h)
            with self._lock:
                if h.idle_flag or age < thr:
                    if h.stalled:
                        h.stalled = False
                        logger.info(
                            "[watchdog] loop %s recovered (heartbeat "
                            "age %.1fs < %.1fs)", h.name, age, thr)
                    continue
                if h.stalled:
                    continue  # one episode, one flag
                h.stalled = True
            fired.append(h.name)
            _STALLS.labels(loop=h.kind).inc()
            slow_logger.warning(
                "[watchdog] loop stalled: %s (kind=%s owner=%s) "
                "heartbeat age %.1fs > threshold %.1fs, "
                "consecutive_errors=%d last_error=%s",
                h.name, h.kind, h.owner, age, thr,
                h.consecutive_errors, h.last_error)
        for kind, age in ages.items():
            _HB_AGE.labels(loop=kind).set(round(age, 3))
        for kind in self._hb_kinds - set(ages):
            # no live non-idle loop of this kind this sweep: serve 0,
            # not the stale last observation
            _HB_AGE.labels(loop=kind).set(0.0)
        self._hb_kinds = set(ages)
        with self._lock:
            _STALLED_NOW.set(sum(1 for h in self._handles.values()
                                 if h.stalled))
            _REGISTERED.set(len(self._handles))
        return fired

    # ---- the /debug/tasks + /stats surface --------------------------------

    def snapshot(self) -> list[dict]:
        """Full per-loop state, newest-registered last (GET
        /debug/tasks).  Backlog hints call the loop's own provider
        (WAL backlog bytes, dirty rollup segments, pending compaction
        tasks) — a provider failure is reported, never raised."""
        now = self._clock()
        out = []
        for h in self.handles():
            if h.dead():
                self.deregister(h)
                continue
            d = {
                "name": h.name,
                "kind": h.kind,
                "owner": h.owner,
                "period_s": h.period_s,
                "stall_threshold_s": round(self.resolved_threshold(h), 3),
                "alive": h.alive(),
                "idle": h.idle_flag,
                "stalled": h.stalled,
                "heartbeat_age_s": round(now - h.last_beat, 3),
                "iterations": h.iterations,
                "last_success_age_s": (
                    None if h.last_success is None
                    else round(now - h.last_success, 3)),
                "consecutive_errors": h.consecutive_errors,
                "last_error": h.last_error,
                "last_error_age_s": (
                    None if h.last_error_at is None
                    else round(now - h.last_error_at, 3)),
            }
            if h.backlog is not None:
                try:
                    d["backlog"] = h.backlog()
                except Exception as exc:  # noqa: BLE001 — hint only
                    d["backlog"] = {"error": str(exc)}
            out.append(d)
        return out

    def summary(self) -> dict:
        """Compact health rollup for /stats: registered/stalled counts,
        the stalled + erroring names, and the oldest non-idle
        heartbeat."""
        now = self._clock()
        stalled, erroring = [], []
        oldest = 0.0
        hs = [h for h in self.handles() if not h.dead()]
        for h in hs:
            if h.stalled:
                stalled.append(h.name)
            if h.consecutive_errors:
                erroring.append(h.name)
            if not h.idle_flag:
                oldest = max(oldest, now - h.last_beat)
        return {"registered": len(hs), "stalled": sorted(stalled),
                "erroring": sorted(erroring),
                "oldest_heartbeat_age_s": round(oldest, 3)}


loops = LoopRegistry()
