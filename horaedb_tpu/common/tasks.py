"""Reliable cancellation of background loop tasks.

Python < 3.12's `asyncio.wait_for` can SWALLOW a cancellation: when the
inner future completes in the same event-loop tick as the cancel
(bpo-37658), wait_for returns the result and the task keeps running —
with the one-shot `Task.cancel()` already spent.  Every background loop
here waits on a signal queue via wait_for, and signals race shutdown
by construction (a failed compaction's trigger_more vs close()), so
`cancel(); await task` can hang forever on a loop that went back to
sleep for an hour.  The torture harness (tests/test_torture.py) finds
this in a few hundred schedules.

`cancel_and_wait` re-delivers the cancel until the task actually
finishes — each retry lands while the task is parked at an await, where
cancellation cannot be swallowed twice in a row by the same race.
"""

from __future__ import annotations

import asyncio


async def cancel_and_wait(task: asyncio.Task,
                          poll_s: float = 0.05) -> None:
    """Cancel `task` and wait for it to finish, re-cancelling if a
    wait_for race swallowed the first delivery.  Never raises the
    task's CancelledError into the caller."""
    while not task.done():
        task.cancel()
        # asyncio.wait never raises; it returns on completion or timeout
        await asyncio.wait([task], timeout=poll_s)
    if task.cancelled():
        return
    exc = task.exception()
    if exc is not None and not isinstance(exc, asyncio.CancelledError):
        raise exc
