"""Request-lifecycle deadlines (docs/robustness.md, query-path
failure domains).

One `Deadline` is minted at HTTP ingress and threaded — via an ambient
`contextvars.ContextVar`, so layers that never knew about deadlines need
no signature changes — down through the engine, the cluster
scatter-gather, and every remote RPC:

  * `remaining()` / `budget(cap)` turn the absolute deadline into
    per-sub-call budgets (an RPC gets `min(rpc_timeout, remaining)`, so
    a retry never outlives the request that asked for it);
  * `checkpoint()` is the cooperative cancellation point sprinkled
    through long host loops (storage merge-scan segments/windows): a
    query observes its own expiry within one checkpoint interval
    instead of running a doomed scan to completion;
  * `cancel()` is the explicit token — admission shedding and client
    disconnects flip it so in-flight work can stop at its next
    checkpoint.

The contextvar propagates into `asyncio.create_task` children
automatically (context is copied at task creation), which is exactly
the fan-out shape of scatter-gather and prefetch pipelines.  Worker
-pool threads do NOT inherit it — by design: pool jobs are bounded
CPU slices and checkpointing belongs in the async loops that schedule
them.
"""

from __future__ import annotations

import contextvars
import time
from typing import Optional

from horaedb_tpu.common.error import Error


class DeadlineExceeded(Error):
    """A cooperative checkpoint observed an expired or cancelled
    deadline.  Subclasses Error so framework-level catches treat it as
    an ordinary failure; the server middleware maps it to HTTP 504."""


class Deadline:
    """Absolute deadline (monotonic clock) + cancellation token."""

    __slots__ = ("deadline_at", "reason", "_cancelled")

    def __init__(self, deadline_at: Optional[float],
                 reason: str = "request"):
        # None = unbounded (a pure cancellation token)
        self.deadline_at = deadline_at
        self.reason = reason
        self._cancelled = False

    @classmethod
    def after(cls, timeout_s: Optional[float],
              reason: str = "request") -> "Deadline":
        """Deadline `timeout_s` from now; None -> unbounded."""
        if timeout_s is None:
            return cls(None, reason)
        return cls(time.monotonic() + max(0.0, timeout_s), reason)

    def cancel(self) -> None:
        self._cancelled = True

    @property
    def cancelled(self) -> bool:
        return self._cancelled

    @property
    def expired(self) -> bool:
        if self._cancelled:
            return True
        return (self.deadline_at is not None
                and time.monotonic() >= self.deadline_at)

    def remaining(self) -> Optional[float]:
        """Seconds left (>= 0.0), or None when unbounded."""
        if self.deadline_at is None:
            return None
        return max(0.0, self.deadline_at - time.monotonic())

    def budget(self, cap_s: Optional[float]) -> Optional[float]:
        """Sub-call budget: the smaller of `cap_s` and the remaining
        time; None only when BOTH are unbounded.  This is what keeps a
        per-RPC timeout from outliving its request."""
        rem = self.remaining()
        if rem is None:
            return cap_s
        if cap_s is None:
            return rem
        return min(cap_s, rem)

    def check(self) -> None:
        """Raise DeadlineExceeded if cancelled or out of time."""
        if self._cancelled:
            raise DeadlineExceeded(f"{self.reason} cancelled")
        if self.deadline_at is not None \
                and time.monotonic() >= self.deadline_at:
            raise DeadlineExceeded(f"{self.reason} deadline exceeded")

    def __repr__(self) -> str:
        rem = self.remaining()
        state = "cancelled" if self._cancelled else (
            "unbounded" if rem is None else f"{rem:.3f}s left")
        return f"Deadline({self.reason}: {state})"


_CURRENT: contextvars.ContextVar[Optional[Deadline]] = \
    contextvars.ContextVar("horaedb_deadline", default=None)


def current_deadline() -> Optional[Deadline]:
    """The ambient deadline, or None outside any request scope."""
    return _CURRENT.get()


class deadline_scope:
    """Bind a deadline as the ambient one for the `with` body (sync or
    async code — contextvars work in both).  Re-entrant: an inner scope
    shadows, never replaces, the outer one."""

    __slots__ = ("deadline", "_token")

    def __init__(self, deadline: Optional[Deadline]):
        self.deadline = deadline
        self._token = None

    def __enter__(self) -> Optional[Deadline]:
        self._token = _CURRENT.set(self.deadline)
        return self.deadline

    def __exit__(self, *exc) -> None:
        _CURRENT.reset(self._token)


# cooperative-cancellation hooks: other ambient budgets (the tenant
# scan-byte quota, common/tenant.py) raise at the SAME checkpoints the
# deadline machinery uses, so every long host loop that is deadline
# -aware is automatically quota-aware — no second set of call sites to
# keep in sync.  Hooks must be cheap no-ops outside their own scope.
_CHECKPOINT_HOOKS: tuple = ()


def add_checkpoint_hook(fn) -> None:
    global _CHECKPOINT_HOOKS
    if fn not in _CHECKPOINT_HOOKS:
        _CHECKPOINT_HOOKS = _CHECKPOINT_HOOKS + (fn,)


def checkpoint() -> None:
    """Cooperative cancellation point: a cheap no-op when no deadline
    is bound, else raises DeadlineExceeded once it has lapsed.  Long
    host-side loops (merge-scan segments, gather merges) call this once
    per iteration.  Registered budget hooks (tenant quotas) fire here
    too, deadline bound or not."""
    dl = _CURRENT.get()
    if dl is not None:
        dl.check()
    for fn in _CHECKPOINT_HOOKS:
        fn()


def remaining_budget(cap_s: Optional[float]) -> Optional[float]:
    """`min(cap_s, ambient remaining)` — the one-liner sub-call budget.
    Returns `cap_s` unchanged when no deadline is bound."""
    dl = _CURRENT.get()
    if dl is None:
        return cap_s
    return dl.budget(cap_s)
