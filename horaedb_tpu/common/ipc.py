"""Arrow IPC stream serialization — the single home for the cluster's
wire format (write plane and query plane must not drift).

Downsample grids also travel as Arrow (downsample_to_arrow /
downsample_from_arrow): one row per series, each aggregate a
FixedSizeList<f64>[num_buckets] column.  The JSON grid encoding turns
every f64 cell into decimal text (and NaN into null) — 2.6x the zstd'd
Arrow bytes even on incompressible random grids, more on real data."""

from __future__ import annotations

import io
from typing import Optional, Union

import numpy as np
import pyarrow as pa
import pyarrow.ipc

COMPRESSIONS = (None, "zstd", "lz4")


def serialize_stream(data: Union[pa.Table, pa.RecordBatch],
                     compression: Optional[str] = None) -> bytes:
    """Serialize a Table/RecordBatch as an IPC stream, optionally with
    compressed buffers.  Compression is OPT-IN per message: readers
    auto-detect, but not every Arrow implementation ships every codec,
    so public endpoints only compress when the client asked."""
    if compression not in COMPRESSIONS:
        raise ValueError(f"unsupported IPC compression {compression!r}; "
                         f"expected one of {COMPRESSIONS}")
    sink = io.BytesIO()
    opts = pyarrow.ipc.IpcWriteOptions(compression=compression)
    with pyarrow.ipc.new_stream(sink, data.schema, options=opts) as writer:
        if isinstance(data, pa.RecordBatch):
            writer.write_batch(data)
        else:
            writer.write_table(data)
    return sink.getvalue()


def downsample_to_arrow(out: dict) -> pa.Table:
    """Encode a query_downsample result ({tsids, num_buckets, aggs:
    {name: (n, num_buckets) float grid}}) as an Arrow table.  NaN cells
    stay NaN (no None round trip)."""
    nb = max(1, int(out["num_buckets"]))
    tsids = np.asarray(out["tsids"], dtype=np.uint64)
    n = len(tsids)
    cols: dict = {"tsid": pa.array(tsids, type=pa.uint64())}
    for name, grid in out["aggs"].items():
        g = np.ascontiguousarray(np.asarray(grid, dtype=np.float64))
        g = g.reshape(n, nb) if n else np.zeros((0, nb))
        cols[f"agg_{name}"] = pa.FixedSizeListArray.from_arrays(
            pa.array(g.reshape(-1), type=pa.float64()), nb)
    return pa.table(cols, metadata={
        b"num_buckets": str(int(out["num_buckets"])).encode()})


def downsample_from_arrow(tbl: pa.Table) -> dict:
    """Inverse of downsample_to_arrow."""
    meta = tbl.schema.metadata or {}
    if b"num_buckets" not in meta:
        raise ValueError(
            "downsample table missing num_buckets metadata "
            "(malformed peer response)")
    nb = int(meta[b"num_buckets"])
    tsids = tbl.column("tsid").to_numpy(zero_copy_only=False)
    n = len(tsids)
    aggs = {}
    for name in tbl.schema.names:
        if not name.startswith("agg_"):
            continue
        col = tbl.column(name).combine_chunks()
        # width comes from the FixedSizeList type itself so the grid
        # shape always matches what the peer encoded (nb==0 encodes as
        # width-1 grids; trusting metadata alone would mis-reshape)
        width = col.type.list_size
        flat = col.values.to_numpy(zero_copy_only=False)
        aggs[name[len("agg_"):]] = flat.reshape(n, width)
    return {"tsids": [int(t) for t in tsids], "num_buckets": nb,
            "aggs": aggs}
