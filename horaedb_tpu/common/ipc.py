"""Arrow IPC stream serialization — the single home for the cluster's
wire format (write plane and query plane must not drift)."""

from __future__ import annotations

import io
from typing import Optional, Union

import pyarrow as pa
import pyarrow.ipc

COMPRESSIONS = (None, "zstd", "lz4")


def serialize_stream(data: Union[pa.Table, pa.RecordBatch],
                     compression: Optional[str] = None) -> bytes:
    """Serialize a Table/RecordBatch as an IPC stream, optionally with
    compressed buffers.  Compression is OPT-IN per message: readers
    auto-detect, but not every Arrow implementation ships every codec,
    so public endpoints only compress when the client asked."""
    if compression not in COMPRESSIONS:
        raise ValueError(f"unsupported IPC compression {compression!r}; "
                         f"expected one of {COMPRESSIONS}")
    sink = io.BytesIO()
    opts = pyarrow.ipc.IpcWriteOptions(compression=compression)
    with pyarrow.ipc.new_stream(sink, data.schema, options=opts) as writer:
        if isinstance(data, pa.RecordBatch):
            writer.write_batch(data)
        else:
            writer.write_table(data)
    return sink.getvalue()
