"""Process-wide monotonic id allocation.

Both SST file ids and manifest delta filenames come from wall-clock-seeded
monotonic u64 counters ("mustn't go backwards on restarts", ref:
src/storage/src/sst.rs:36-46, manifest/mod.rs:52-63): monotonicity across
restarts is what makes a file id usable as the write sequence.
"""

from __future__ import annotations

import itertools
import threading
import time

_U64_MASK = (1 << 64) - 1


class MonotonicIdAllocator:
    def __init__(self) -> None:
        self._counter = itertools.count(time.time_ns() & _U64_MASK)
        self._lock = threading.Lock()

    def allocate(self) -> int:
        with self._lock:
            return next(self._counter) & _U64_MASK
