"""Continuous queries: standing downsample rollup tiers fed by the
ingest path (see rollup/manager.py for the architecture and
docs/rollups.md for the correctness contract)."""

from horaedb_tpu.rollup.config import RollupConfig, rollup_from_dict
from horaedb_tpu.rollup.manager import (CELL_SCHEMA, ROLLUP_AGGS,
                                        RollupManager, RollupSpec)

__all__ = ["CELL_SCHEMA", "ROLLUP_AGGS", "RollupConfig", "RollupManager",
           "RollupSpec", "rollup_from_dict"]
