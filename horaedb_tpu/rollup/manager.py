"""Standing rollups: incremental materialized downsample tiers.

A standing query registered per (metric, field) is maintained as
pre-aggregated cells — one row per (metric_id, tsid, field_id,
bucket_ts) holding the count/sum/min/max/last partials the downsample
grid needs — in one extra Overwrite-mode table per tier (e.g. 1m and
1h), stored alongside the raw SSTs and riding the SAME manifest,
compaction, scrub and cache machinery (ROADMAP open item 4; TiLT's
compile-once/feed-deltas shape, PAPERS.md).

Maintenance is SEGMENT-granular and recompute-from-raw:

  write/flush  -> the engine notes the touched raw segments dirty
  roll pass    -> every dirty/unfingerprinted segment without a live
                  memtable is re-aggregated from raw SSTs through the
                  engine's own downsample pushdown, its cells written
                  (Overwrite: a re-roll supersedes old cells under the
                  normal last-value `__seq__` discipline), and its SST
                  fingerprint recorded
  state        -> {seq watermark, segment -> SST-id fingerprint} is
                  persisted to the object store only AFTER the cells
                  land; a crash in between just re-rolls (idempotent)

Crash safety follows the WAL discipline (docs/robustness.md): rollup
state never trusts a partial update — on open, any segment whose
current SST set differs from its recorded fingerprint is dirty again,
and acked-but-unflushed rows are excluded via the live memtable map,
so recovery recomputes from raw instead of serving a half-rolled tier.

Serving: the planner (metric_engine.query_downsample) consults
`covers()` + `try_serve()`.  A query is rollup-served when its bucket
matches a tier exactly and its range is bucket-aligned; covered
segments read cells, while dirty/unrolled segments — the not-yet-
rolled-up tail — are recomputed from raw through the same pushdown the
raw path uses, so the assembled grid is BIT-IDENTICAL to a from-raw
recompute (the correctness contract, enforced by the seeded
interleaving tests; docs/rollups.md).

All rollup-tier reads go through this module's coverage API —
tools/lint.py rejects direct rollup-table scans elsewhere.
"""

from __future__ import annotations

import asyncio
import json
import logging
import time
from dataclasses import dataclass, field as dc_field
from typing import Optional

import numpy as np
import pyarrow as pa

from horaedb_tpu.common.error import ensure
from horaedb_tpu.common.loops import loops
from horaedb_tpu.common.memledger import ledger as memledger
from horaedb_tpu.objstore import NotFoundError, ObjectStore
from horaedb_tpu.ops import And, Eq, In, TimeRangePred
from horaedb_tpu.ops.downsample import ALL_AGGS
from horaedb_tpu.rollup.config import RollupConfig
from horaedb_tpu.storage.read import ScanRequest
from horaedb_tpu.storage.types import TimeRange, Timestamp
from horaedb_tpu.utils import (WIDE_BUCKETS, op_trace, registry, span,
                               trace_add)

logger = logging.getLogger(__name__)

# the partials every grid aggregate derives from (avg = sum/count at
# assembly, exactly the raw combine's formula); maintenance requests
# these so stored cells are `which`-independent
ROLLUP_AGGS = ("count", "last", "max", "min", "sum")

# The scan path encodes float value columns to f32 on device (the
# engine-wide convention, ops/encode.py), so a stored cell value only
# survives the write->scan round trip if it is exactly
# f32-representable.  min/max/last ARE (they equal some f32-encoded
# sample value, and the per-window partial grids are f32 by design);
# the f64 accumulators count/sum are NOT, so they are stored as an
# exact three-way float32 split (24*3 bits > the 53-bit f64 mantissa:
# hi = f32(v), md = f32(v - hi), lo = v - hi - md; summing the parts
# back in f64 is exact because they never overlap), and last_ts is
# stored relative to its bucket start (an integer < tier_ms < 2^24,
# f32-exact) and rebased at assembly.
_CELL_VALUE_COLS = ("count_hi", "count_md", "count_lo",
                    "sum_hi", "sum_md", "sum_lo",
                    "min", "max", "last", "last_ts_rel")

# cell schema: PK (metric_id, tsid, field_id, bucket_ts) + the stored
# partials.  Overwrite mode: a re-rolled bucket's new cell supersedes
# the old one in the merge, like any other last-value update.
CELL_SCHEMA = pa.schema(
    [("metric_id", pa.uint64()), ("tsid", pa.uint64()),
     ("field_id", pa.uint64()), ("bucket_ts", pa.int64())]
    + [(c, pa.float64()) for c in _CELL_VALUE_COLS])
CELL_NUM_PKS = 4

# a tier bucket must stay under 2^24 ms (~4.6 h) so last_ts_rel is an
# exactly f32-representable integer
_TIER_MS_MAX = 1 << 24


def _split3(v: np.ndarray) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Exact triple-float split of finite f64 values: v == hi + md + lo
    with every part f32-representable (so each survives the scan
    path's f32 encode) and the f64 re-sum exact."""
    hi = v.astype(np.float32).astype(np.float64)
    r = v - hi
    md = r.astype(np.float32).astype(np.float64)
    lo = r - md
    return hi, md, lo

_SERVED = registry.counter(
    "rollup_served_queries_total",
    "downsample queries answered from a rollup tier "
    "(labels: table=metric, tier)")
_FALLBACK = registry.counter(
    "rollup_fallback_queries_total",
    "rollup-shaped queries that fell back to the raw scan "
    "(no covered segment)")
_PASSES = registry.counter(
    "rollup_roll_passes_total", "rollup maintenance passes")
_SEGMENTS_ROLLED = registry.counter(
    "rollup_segments_rolled_total",
    "raw segments (re)aggregated into rollup cells")
_CELLS_WRITTEN = registry.counter(
    "rollup_cells_written_total",
    "pre-aggregated cells written to rollup tiers")
_ROLL_SECONDS = registry.histogram(
    "rollup_roll_seconds",
    "per-segment roll latency (aggregate from raw + cell writes, all "
    "tiers)", buckets=WIDE_BUCKETS)
_LAG = registry.gauge(
    "rollup_lag_seqs",
    "newest raw write seq minus the newest seq incorporated into the "
    "rollup (labels: table=metric, field)")


async def _collect(stream) -> list[pa.RecordBatch]:
    return [b async for b in stream]


@dataclass
class RollupSpec:
    """One standing downsample query + its maintenance state."""

    metric: str
    field: str
    metric_id: int
    field_id: int
    # seg_start -> sorted SST-id fingerprint at roll time (persisted)
    rolled: dict[int, list[int]] = dc_field(default_factory=dict)
    # newest raw seq incorporated at the last successful pass (persisted)
    seq: int = 0
    # segments noted dirty since the last pass (in-memory; recovered on
    # open by diffing fingerprints against the live manifest)
    dirty: set[int] = dc_field(default_factory=set)
    # segments whose re-roll is IN FLIGHT this pass: they left `dirty`
    # with the pass's snapshot but their fresh cells have not committed
    # yet, so coverage must keep treating them as dirty (serving their
    # old cells mid-re-roll would drop the rows the re-roll is adding)
    rolling: set[int] = dc_field(default_factory=set)
    # segments whose grid values cannot round-trip the cell encoding
    # (e.g. a sum beyond float32 range): permanently raw-served — never
    # covered, never re-roll-churned — until new data dirties them
    unrollable: set[int] = dc_field(default_factory=set)
    served_queries: int = 0
    fallback_queries: int = 0

    @property
    def key(self) -> tuple[str, str]:
        return (self.metric, self.field)


class RollupManager:
    """Owns the tier tables, the standing-query registry, the
    maintenance loop, and the serve-time coverage API."""

    def __init__(self, tiers: dict[int, object], tier_names: dict[int, str],
                 store: ObjectStore, state_prefix: str, segment_ms: int,
                 config: RollupConfig, data_table):
        self.tiers = tiers  # tier_ms -> CloudObjectStorage
        self.tier_names = tier_names
        self.store = store
        self.state_prefix = state_prefix.rstrip("/")
        self.segment_ms = segment_ms
        self.config = config
        self._data = data_table
        self._engine = None  # attach() after MetricEngine construction
        self.specs: dict[tuple[str, str], RollupSpec] = {}
        self._roll_lock = asyncio.Lock()
        self._wake: Optional[asyncio.Event] = None
        self._task: Optional[asyncio.Task] = None
        self._stopping = False

    # ---- lifecycle --------------------------------------------------------

    @classmethod
    async def open(cls, root_path: str, store: ObjectStore, segment_ms: int,
                   config: RollupConfig, storage_config, runtimes,
                   data_table) -> "RollupManager":
        import dataclasses

        from horaedb_tpu.storage.config import StorageConfig, UpdateMode
        from horaedb_tpu.storage.storage import CloudObjectStorage

        tier_ms_list = config.tier_millis()
        for t in tier_ms_list:
            ensure(segment_ms % t == 0,
                   f"[rollup] tier {t}ms must evenly divide the segment "
                   f"duration ({segment_ms}ms): maintenance and serving "
                   "are segment-granular")
            ensure(t < _TIER_MS_MAX,
                   f"[rollup] tier {t}ms too coarse: bucket-relative "
                   f"last_ts must stay f32-exact (< {_TIER_MS_MAX}ms)")
        cfg = dataclasses.replace(storage_config or StorageConfig(),
                                  update_mode=UpdateMode.OVERWRITE)
        tiers: dict[int, object] = {}
        names: dict[int, str] = {}
        try:
            for name, tier_ms in zip(config.tiers, tier_ms_list):
                tiers[tier_ms] = await CloudObjectStorage.open(
                    f"{root_path}/rollup/{name}", segment_ms, store,
                    CELL_SCHEMA, CELL_NUM_PKS, cfg, runtimes=runtimes)
                names[tier_ms] = name
        except BaseException:
            for t in tiers.values():
                await t.close()
            raise
        self = cls(tiers, names, store, f"{root_path}/rollup/_state",
                   segment_ms, config, data_table)
        try:
            await self._recover()
            for metric, fld in config.spec_pairs():
                if (metric, fld) not in self.specs:
                    await self.register(metric, fld)
        except BaseException:
            # a failed recover/registration must not leak the tier
            # tables' compaction schedulers
            for t in tiers.values():
                await t.close()
            raise
        self._wake = asyncio.Event()
        # threshold sized to a whole-table registration backfill, the
        # longest legitimate pass
        self._task = loops.spawn(
            self._loop, name=f"rollup:{root_path}", kind="rollup",
            owner="rollup", period_s=config.roll_interval.seconds,
            stall_threshold_s=600.0, backlog=self._backlog)
        # memory plane (common/memledger.py): the maintenance state —
        # per-segment SST-id fingerprints + dirty/rolling/unrollable
        # sets — grows with segment count and must be visible on the
        # 1B ladder (the tier TABLES' caches register via their own
        # readers)
        self._mem_account = memledger.register(
            f"rollup_state:{root_path}",
            lambda m: m.state_bytes(), anchor=self,
            kind="rollup_state", owner=root_path)
        if self.specs:
            # recovered/config-registered specs may have pending work
            # (their register()-time wake predates the event existing)
            self.wake()
        return self

    def attach(self, engine) -> None:
        """Back-reference to the MetricEngine whose downsample pushdown
        performs both maintenance recomputes and raw-tail serving."""
        self._engine = engine

    async def close(self) -> None:
        self._stopping = True
        if self._task is not None:
            self._wake.set()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        for t in self.tiers.values():
            await t.close()
        memledger.deregister(getattr(self, "_mem_account", None))
        self._mem_account = None

    def state_bytes(self) -> int:
        """Estimated host bytes of the in-memory maintenance state
        (the ledger's pull gauge).  Fingerprints dominate: one int
        list per rolled segment per spec.  An estimate — 28 B per
        small int + 56 B list header — not sys.getsizeof recursion,
        which would walk every element on every sampler round."""
        total = 0
        for spec in self.specs.values():
            total += 64 * (len(spec.dirty) + len(spec.rolling)
                           + len(spec.unrollable))
            total += sum(56 + 28 * len(ids)
                         for ids in spec.rolled.values())
        return total

    async def _recover(self) -> None:
        """Load persisted specs; any rolled segment whose CURRENT SST
        set differs from its recorded fingerprint is dirty again — the
        never-trust-a-partial-update discipline."""
        try:
            listing = await self.store.list(self.state_prefix + "/")
        except NotFoundError:
            listing = []
        for meta in listing:
            try:
                data = json.loads(await self.store.get(meta.path))
                spec = RollupSpec(
                    metric=data["metric"], field=data["field"],
                    metric_id=int(data["metric_id"]),
                    field_id=int(data["field_id"]),
                    rolled={int(k): [int(i) for i in v]
                            for k, v in data.get("rolled", {}).items()},
                    seq=int(data.get("seq", 0)))
            except (KeyError, ValueError, TypeError) as exc:
                logger.warning("rollup: dropping unreadable state %s: %s",
                               meta.path, exc)
                continue
            self.specs[spec.key] = spec
        if self.specs:
            by_seg = await self._data_fingerprints()
            for spec in self.specs.values():
                stale = {seg for seg, fp in spec.rolled.items()
                         if by_seg.get(seg) != fp}
                spec.dirty |= stale
                if stale:
                    logger.info(
                        "rollup %s:%s: %d segment(s) changed since the "
                        "last pass; re-rolling", spec.metric, spec.field,
                        len(stale))

    # ---- registration -----------------------------------------------------

    async def register(self, metric: str, field: str = "value"
                       ) -> RollupSpec:
        """Register a standing downsample query.  Idempotent; the
        initial backfill happens on the next maintenance pass (or an
        explicit roll_now)."""
        from horaedb_tpu.metric_engine.types import field_id_of, metric_id_of

        ensure(bool(metric), "rollup metric must be non-empty")
        spec = self.specs.get((metric, field))
        if spec is None:
            spec = RollupSpec(metric=metric, field=field,
                              metric_id=metric_id_of(metric),
                              field_id=field_id_of(field))
            self.specs[spec.key] = spec
            await self._persist(spec)
            logger.info("rollup registered: %s:%s (tiers %s)", metric,
                        field, sorted(self.tiers))
        self.wake()
        return spec

    async def unregister(self, metric: str, field: str = "value") -> bool:
        spec = self.specs.pop((metric, field), None)
        if spec is None:
            return False
        try:
            await self.store.delete(self._state_path(spec))
        except NotFoundError:
            pass
        return True

    def _state_path(self, spec: RollupSpec) -> str:
        return (f"{self.state_prefix}/"
                f"{spec.metric_id:016x}_{spec.field_id:016x}.json")

    async def _persist(self, spec: RollupSpec) -> None:
        payload = json.dumps({
            "metric": spec.metric, "field": spec.field,
            "metric_id": spec.metric_id, "field_id": spec.field_id,
            "seq": spec.seq,
            "rolled": {str(k): v for k, v in sorted(spec.rolled.items())},
        }).encode()
        await self.store.put(self._state_path(spec), payload)

    # ---- delta feed -------------------------------------------------------

    def note_write(self, segs_by_metric: dict) -> None:
        """Ingest-path hook: rows were just acked — mark exactly the
        segments that received samples dirty, per metric (a dense range
        would let one out-of-order backfill row dirty — and force a
        re-roll of — every segment in between).  O(specs) on the ack
        path."""
        woke = False
        for spec in self.specs.values():
            segs = segs_by_metric.get(spec.metric)
            if segs:
                spec.dirty |= segs
                spec.unrollable -= segs  # new data: worth re-trying
                woke = True
        if woke:
            self.wake()

    def note_flush(self, segment_start: int) -> None:
        """A memtable just drained to an SST: the segment becomes
        rollable (it was dirty since its writes acked)."""
        del segment_start
        self.wake()

    def wake(self) -> None:
        if self._wake is not None:
            self._wake.set()

    # ---- maintenance ------------------------------------------------------

    def _backlog(self) -> dict:
        """/debug/tasks hint: segments awaiting (or refused) a roll."""
        return {
            "dirty_segments": sum(len(s.dirty)
                                  for s in self.specs.values()),
            "rolling_segments": sum(len(s.rolling)
                                    for s in self.specs.values()),
            "unrollable_segments": sum(len(s.unrollable)
                                       for s in self.specs.values()),
            "specs": len(self.specs),
        }

    async def _loop(self, hb) -> None:
        interval = self.config.roll_interval.seconds
        while not self._stopping:
            try:
                await asyncio.wait_for(self._wake.wait(), interval)
            except asyncio.TimeoutError:
                pass
            hb.beat()
            self._wake.clear()
            if self._stopping:
                return
            try:
                await self.roll_now()
                hb.ok()
            except asyncio.CancelledError:
                raise
            except Exception as exc:  # noqa: BLE001 — retried next tick
                hb.error(exc)
                logger.exception("rollup maintenance pass failed")

    async def _data_fingerprints(self) -> dict[int, list[int]]:
        from horaedb_tpu.storage.sst import segment_of

        ssts = await self._data.manifest.all_ssts()
        by_seg: dict[int, list[int]] = {}
        for f in ssts:
            by_seg.setdefault(segment_of(f, self.segment_ms),
                              []).append(f.id)
        return {seg: sorted(ids) for seg, ids in by_seg.items()}

    def _memtable_segments(self) -> set[int]:
        fn = getattr(self._data, "memtable_segments", None)
        return fn() if fn is not None else set()

    async def roll_now(self) -> dict:
        """One maintenance pass over every spec: recompute dirty /
        unfingerprinted segments from raw, write their cells, persist
        state.  Returns {spec_key: segments_rolled}."""
        ensure(self._engine is not None, "rollup manager not attached")
        out = {}
        async with self._roll_lock:
            _PASSES.inc()
            # one op trace per maintenance pass: the recompute scans'
            # objstore/cache traffic and per-segment rollup_roll spans
            # attribute to it (a traced admin request keeps the scope)
            with op_trace("rollup_pass", slow_s=600.0,
                          specs=len(self.specs)):
                for spec in list(self.specs.values()):
                    rolled = await self._roll_spec(spec)
                    out[f"{spec.metric}:{spec.field}"] = rolled
        return out

    async def _roll_spec(self, spec: RollupSpec) -> int:
        # snapshot the pending notes: anything arriving mid-pass lands
        # in the fresh set and survives to the next pass.  Snapshotted
        # segments immediately enter `rolling` so coverage keeps
        # treating them as dirty until their fresh cells commit.
        taken, spec.dirty = spec.dirty, set()
        spec.rolling |= taken
        done = 0
        try:
            by_seg = await self._data_fingerprints()
            mem_segs = self._memtable_segments()
            target = (set(by_seg) | taken) - spec.unrollable
            to_roll = sorted(
                seg for seg in target
                if seg in taken or spec.rolled.get(seg) != by_seg.get(seg))
            spec.rolling |= set(to_roll)
            # acked-but-unflushed rows keep their segment dirty: reads
            # get them through the raw tail until the flush lands
            to_roll = [seg for seg in to_roll if seg not in mem_segs]
            for seg in to_roll:
                t0 = time.perf_counter()
                with span("rollup_roll", metric=spec.metric,
                          segment=seg):
                    ok = await self._roll_segment(spec, seg)
                spec.rolling.discard(seg)
                if not ok:
                    # grid values that cannot round-trip the cell
                    # encoding: this segment stays raw-served (not
                    # dirty — that would re-scan it every pass) until
                    # new data arrives
                    spec.unrollable.add(seg)
                    spec.rolled.pop(seg, None)
                    continue
                spec.rolled[seg] = by_seg.get(seg, [])
                done += 1
                _SEGMENTS_ROLLED.inc()
                _ROLL_SECONDS.observe(time.perf_counter() - t0)
        finally:
            # an interrupted or partial pass leaves every unfinished
            # segment dirty, never half-covered
            spec.dirty |= spec.rolling
            spec.rolling.clear()
            if done:
                incorporated = [i for ids in spec.rolled.values()
                                for i in ids]
                spec.seq = max([spec.seq] + incorporated)
                await self._persist(spec)
                await self._refresh_lag(spec)
        return done

    async def _roll_segment(self, spec: RollupSpec, seg: int) -> bool:
        """Recompute one raw segment's cells for every tier, through
        the engine's OWN downsample pushdown — the one code path both
        the raw queries and the maintenance use, which is what makes
        rollup-served grids bit-identical to a from-raw recompute.
        False when the segment's values cannot be stored faithfully."""
        rng = TimeRange.new(seg, seg + self.segment_ms)
        pred = And([Eq("metric_id", spec.metric_id),
                    Eq("field_id", spec.field_id)])
        for tier_ms, table in sorted(self.tiers.items()):
            nb = self.segment_ms // tier_ms
            out = await self._engine._scan_downsample(
                pred, rng, tier_ms, nb, ROLLUP_AGGS)
            if not await self._write_cells(spec, table, tier_ms, seg,
                                           out):
                return False
            if not out["tsids"]:
                # no rows of this metric in the segment: every tier is
                # empty — skip the remaining tiers' scans (registration
                # backfill sweeps the whole table, and most segments
                # hold only other metrics' data)
                break
        return True

    async def _write_cells(self, spec: RollupSpec, table, tier_ms: int,
                           seg: int, out: dict) -> bool:
        from horaedb_tpu.storage.storage import WriteRequest

        if not out["tsids"]:
            return True
        grids = out["aggs"]
        tsids = np.asarray(out["tsids"], dtype=np.uint64)
        gi, bi = np.nonzero(grids["count"] > 0)
        if len(gi) == 0:
            return True
        bucket_ts = seg + bi.astype(np.int64) * tier_ms
        n = len(gi)

        def cell(name: str) -> np.ndarray:
            return np.ascontiguousarray(
                grids[name][gi, bi].astype(np.float64, copy=False))

        count, sum_ = cell("count"), cell("sum")
        cols = (_split3(count) + _split3(sum_)
                + (cell("min"), cell("max"), cell("last"),
                   cell("last_ts") - bucket_ts))
        # enforce the bit-identical contract at WRITE time: simulate
        # the read path's f32 value-column encode over every stored
        # column and require the accumulators to reassemble exactly —
        # a value that cannot round-trip (e.g. a sum beyond f32 range)
        # would silently diverge from the raw path, so its segment
        # stays raw-served instead
        rb = [c.astype(np.float32).astype(np.float64) for c in cols]
        faithful = all(np.array_equal(a, b, equal_nan=True)
                       for a, b in zip(cols, rb)) \
            and np.array_equal((rb[0] + rb[1]) + rb[2], count,
                               equal_nan=True) \
            and np.array_equal((rb[3] + rb[4]) + rb[5], sum_,
                               equal_nan=True)
        if not faithful:
            logger.warning(
                "rollup %s:%s segment %d: grid values cannot round-trip "
                "the cell encoding; segment stays raw-served",
                spec.metric, spec.field, seg)
            return False
        batch = pa.record_batch(
            [pa.array(np.full(n, spec.metric_id, dtype=np.uint64)),
             pa.array(tsids[gi]),
             pa.array(np.full(n, spec.field_id, dtype=np.uint64)),
             pa.array(bucket_ts, type=pa.int64())]
            + [pa.array(c) for c in cols],
            schema=CELL_SCHEMA)
        await table.write(WriteRequest(
            batch, TimeRange.new(int(bucket_ts.min()),
                                 int(bucket_ts.max()) + tier_ms)))
        _CELLS_WRITTEN.inc(n)
        trace_add("rollup_cells_rows", n)
        return True

    async def _refresh_lag(self, spec: RollupSpec) -> None:
        newest = await self._newest_raw_seq()
        _LAG.labels(table=spec.metric,
                    field=spec.field).set(self._lag(spec, newest))

    async def _newest_raw_seq(self) -> int:
        ssts = await self._data.manifest.all_ssts()
        newest = max([f.meta.max_sequence for f in ssts], default=0)
        return max(newest, getattr(self._data, "last_seq", 0))

    def _lag(self, spec: RollupSpec, newest: int) -> int:
        """Newest raw seq minus the true incorporation watermark: the
        max rolled SST id, FLOORED by the oldest acked-but-unflushed
        seq — rows sitting in memtables are not in any tier, and a
        later flush must not make the tier read as caught-up."""
        w = spec.seq
        oldest_fn = getattr(self._data, "oldest_unflushed_seq", None)
        if oldest_fn is not None:
            oldest = oldest_fn()
            if oldest is not None:
                w = min(w, oldest - 1)
        return max(0, newest - w)

    # ---- serving ----------------------------------------------------------

    def covers(self, metric: str, field: str, bucket_ms: int,
               time_range: TimeRange) -> bool:
        """Cheap static coverage check the planner gates on: a standing
        query exists, the bucket matches a tier exactly, and the range
        is bucket-aligned (cells live on the absolute bucket grid)."""
        if (metric, field) not in self.specs or bucket_ms not in self.tiers:
            return False
        start, end = int(time_range.start), int(time_range.end)
        return (start >= 0 and end > start
                and start % bucket_ms == 0 and end % bucket_ms == 0)

    async def try_serve(self, metric: str, mid: int,
                        tsids: Optional[set], time_range: TimeRange,
                        bucket_ms: int, field: str,
                        aggs: tuple) -> Optional[dict]:
        """Serve a covered query from rollup cells, with dirty/unrolled
        segments recomputed from raw (the hybrid tail).  Returns None
        when no segment is covered — the caller falls back to the raw
        path wholesale."""
        spec = self.specs.get((metric, field))
        if spec is None or bucket_ms not in self.tiers \
                or not set(aggs) <= set(ALL_AGGS):
            return None
        if mid != spec.metric_id:
            return None  # hash collision paranoia: serve raw
        start, end = int(time_range.start), int(time_range.end)
        nb = (end - start) // bucket_ms
        mem_segs = self._memtable_segments()
        by_seg = await self._data_fingerprints()

        def seg_covered(seg: int) -> bool:
            if (seg in spec.dirty or seg in spec.rolling
                    or seg in mem_segs or seg in spec.unrollable):
                return False
            if seg in spec.rolled:
                return True
            # no SSTs, no buffered rows, never noted: provably empty —
            # trivially covered (contributes nothing), so a range
            # predating the table's first data doesn't read as a
            # mostly-uncovered tail and force the raw fallback
            return seg not in by_seg

        seg0 = int(Timestamp(start).truncate_by(self.segment_ms))
        segs = list(range(seg0, end, self.segment_ms))
        covered = [s for s in segs if seg_covered(s)]
        tail = [s for s in segs if not seg_covered(s)]
        if not covered or len(tail) > len(covered):
            # nothing covered — or a mostly-unrolled range, where N
            # per-segment tail recomputes cost more than the ONE
            # ranged raw scan the fallback runs
            spec.fallback_queries += 1
            _FALLBACK.inc()
            return None
        with span("rollup_serve", metric=metric, tier=bucket_ms,
                  covered=len(covered), tail=len(tail)):
            out = await self._assemble(spec, mid, tsids, start, end,
                                       bucket_ms, nb, set(covered), tail,
                                       tuple(aggs))
        spec.served_queries += 1
        _SERVED.labels(table=metric,
                       tier=self.tier_names[bucket_ms]).inc()
        trace_add("rollup_served", 1)
        trace_add("rollup_tail_segments", len(tail))
        return out

    async def _read_cells(self, spec: RollupSpec, tsids: Optional[set],
                          start: int, end: int, bucket_ms: int,
                          covered: set):
        """Cells of the covered segments in [start, end), as numpy
        columns.  The tier-table scan is the ordinary merge path: a
        re-rolled bucket's latest cell wins by seq like any overwrite."""
        preds = [Eq("metric_id", spec.metric_id),
                 Eq("field_id", spec.field_id),
                 TimeRangePred("bucket_ts", start, end)]
        if tsids is not None:
            preds.append(In("tsid", sorted(tsids)))
        table = self.tiers[bucket_ms]
        batches = await _collect(table.scan(ScanRequest(
            range=TimeRange.new(start, end), predicate=And(preds))))
        if not batches:
            return None
        tbl = pa.Table.from_batches(batches)
        raw = {c: tbl.column(c).to_numpy(zero_copy_only=False)
               for c in ("tsid", "bucket_ts") + _CELL_VALUE_COLS}
        # reassemble the exact f64 accumulators from their f32 splits
        # (non-overlapping parts: the f64 sums are exact) and rebase
        # last_ts from its bucket-relative offset
        cols = {
            "tsid": raw["tsid"], "bucket_ts": raw["bucket_ts"],
            "count": (raw["count_hi"] + raw["count_md"]) + raw["count_lo"],
            "sum": (raw["sum_hi"] + raw["sum_md"]) + raw["sum_lo"],
            "min": raw["min"], "max": raw["max"], "last": raw["last"],
            "last_ts": raw["bucket_ts"] + raw["last_ts_rel"],
        }
        # a dirty segment's stale cells must not leak into the grid —
        # its buckets are recomputed by the raw tail instead
        seg_of = (cols["bucket_ts"] // self.segment_ms) * self.segment_ms
        keep = np.isin(seg_of, np.asarray(sorted(covered), dtype=np.int64))
        if not keep.all():
            cols = {k: v[keep] for k, v in cols.items()}
        return cols if len(cols["tsid"]) else None

    async def _assemble(self, spec: RollupSpec, mid: int,
                        tsids: Optional[set], start: int, end: int,
                        bucket_ms: int, nb: int, covered: set,
                        tail: list, aggs: tuple) -> dict:
        cells = await self._read_cells(spec, tsids, start, end, bucket_ms,
                                       covered)
        # not-yet-rolled-up tail: recompute each segment from raw via
        # the SAME pushdown the raw path runs (IngestStorage flushes
        # overlapping memtables first — flush-then-replan — so acked
        # rows are included)
        tail_parts = []
        preds = [Eq("metric_id", mid), Eq("field_id", spec.field_id)]
        if tsids is not None:
            preds.append(In("tsid", sorted(tsids)))
        # avg is derived from the f64 sum/count accumulators at the end
        # (the raw combine's own formula), so the tail must carry sum
        tail_which = tuple(set(aggs)
                           | ({"sum"} if "avg" in aggs else set()))
        for seg in tail:
            with span("rollup_tail", segment=seg):
                seg_nb = self.segment_ms // bucket_ms
                out = await self._engine._scan_downsample(
                    And(preds), TimeRange.new(seg, seg + self.segment_ms),
                    bucket_ms, seg_nb, tail_which)
            if out["tsids"]:
                tail_parts.append((seg, out))

        tsid_sets = []
        if cells is not None:
            tsid_sets.append(np.unique(cells["tsid"]))
        for _seg, out in tail_parts:
            tsid_sets.append(np.asarray(out["tsids"], dtype=np.uint64))
        if not tsid_sets:
            return {"tsids": [], "num_buckets": nb, "aggs": {}}
        all_tsids = np.unique(np.concatenate(tsid_sets))
        g = len(all_tsids)

        # accumulator grids with the raw combine's empty-cell identities
        count = np.zeros((g, nb), dtype=np.float64)
        sum_ = np.zeros((g, nb), dtype=np.float64)
        min_ = np.full((g, nb), np.inf, dtype=np.float64)
        max_ = np.full((g, nb), -np.inf, dtype=np.float64)
        last = np.full((g, nb), np.nan, dtype=np.float64)
        last_ts = np.full((g, nb), np.nan, dtype=np.float64)

        if cells is not None:
            rows = np.searchsorted(all_tsids, cells["tsid"])
            bcols = (cells["bucket_ts"] - start) // bucket_ms
            count[rows, bcols] = cells["count"]
            sum_[rows, bcols] = cells["sum"]
            min_[rows, bcols] = cells["min"]
            max_[rows, bcols] = cells["max"]
            last[rows, bcols] = cells["last"]
            last_ts[rows, bcols] = cells["last_ts"]

        for seg, out in tail_parts:
            grids = out["aggs"]
            rows = np.searchsorted(
                all_tsids, np.asarray(out["tsids"], dtype=np.uint64))
            # global grid columns this segment overlaps within [start,
            # end); the segment grid's own column j maps via the bucket
            # offset (buckets never straddle segments: tier | segment)
            lo_b = max(seg, start)
            hi_b = min(seg + self.segment_ms, end)
            src = slice((lo_b - seg) // bucket_ms,
                        (hi_b - seg) // bucket_ms)
            dst = slice((lo_b - start) // bucket_ms,
                        (hi_b - start) // bucket_ms)
            count[rows, dst] = grids["count"][:, src]
            if "sum" in grids:
                sum_[rows, dst] = grids["sum"][:, src]
            if "min" in grids:
                min_[rows, dst] = grids["min"][:, src]
            if "max" in grids:
                max_[rows, dst] = grids["max"][:, src]
            if "last" in grids:
                last[rows, dst] = grids["last"][:, src]
                last_ts[rows, dst] = grids["last_ts"][:, src]

        # drop groups with no row in ANY requested bucket — exactly the
        # raw finalize's discipline (a tail segment scan may register a
        # series whose in-range cells are all empty)
        nz = count.sum(axis=1) > 0
        if not nz.all():
            all_tsids = all_tsids[nz]
            count, sum_, min_, max_ = (a[nz] for a in
                                       (count, sum_, min_, max_))
            last, last_ts = last[nz], last_ts[nz]
        if not len(all_tsids):
            return {"tsids": [], "num_buckets": nb, "aggs": {}}

        requested = set(aggs) | {"count"}
        empty = count == 0
        grids_out: dict = {"count": count}
        if "sum" in requested:
            grids_out["sum"] = sum_
        if "avg" in requested:
            with np.errstate(invalid="ignore", divide="ignore"):
                grids_out["avg"] = np.where(empty, np.nan,
                                            sum_ / np.maximum(count, 1))
        if "min" in requested:
            grids_out["min"] = min_
        if "max" in requested:
            grids_out["max"] = max_
        if "last" in requested:
            grids_out["last"] = last
            grids_out["last_ts"] = last_ts
        return {"tsids": [int(t) for t in all_tsids],
                "num_buckets": nb, "aggs": grids_out}

    # ---- observability ----------------------------------------------------

    async def stats(self) -> dict:
        """The /stats surface: per-spec lag (newest raw seq vs newest
        rolled-up seq), segment coverage, serve counters, and per-tier
        cell volume from the tier manifests."""
        by_seg = await self._data_fingerprints()
        mem_segs = self._memtable_segments()
        newest = await self._newest_raw_seq()
        tiers = {}
        for tier_ms, table in sorted(self.tiers.items()):
            ssts = await table.manifest.all_ssts()
            tiers[self.tier_names[tier_ms]] = {
                "bucket_ms": tier_ms,
                "ssts": len(ssts),
                "cell_rows": sum(f.meta.num_rows for f in ssts),
                "bytes": sum(f.meta.size for f in ssts),
            }
        specs = {}
        for spec in self.specs.values():
            lag = self._lag(spec, newest)
            _LAG.labels(table=spec.metric, field=spec.field).set(lag)
            clean = [seg for seg in spec.rolled
                     if seg not in spec.dirty and seg not in spec.rolling
                     and seg not in mem_segs
                     and by_seg.get(seg) == spec.rolled[seg]]
            data_segs = len(set(by_seg) | mem_segs)
            specs[f"{spec.metric}:{spec.field}"] = {
                "metric": spec.metric,
                "field": spec.field,
                "seq_newest_raw": newest,
                "seq_rolled": spec.seq,
                "lag_seqs": lag,
                "data_segments": data_segs,
                "rolled_segments": len(clean),
                "dirty_segments": len(set(spec.dirty) | spec.rolling
                                      | spec.unrollable
                                      | (mem_segs & set(spec.rolled))),
                "coverage": (round(len(clean) / data_segs, 4)
                             if data_segs else 1.0),
                "served_queries": spec.served_queries,
                "fallback_queries": spec.fallback_queries,
            }
        return {"tiers": tiers, "specs": specs}
