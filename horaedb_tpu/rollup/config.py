"""[rollup] configuration: standing downsample queries maintained as
incremental materialized rollup tiers (rollup/manager.py).

No reference analogue — the reference serves every dashboard query from
the raw merge-scan.  With rollups enabled, a standing query registered
per (metric, field) keeps pre-aggregated cells (count/sum/min/max/last
partials per series per bucket) in one extra table per tier, updated
from the ingest path and compacted/scrubbed by the same machinery as
raw SSTs, so repeated dashboard traffic stops re-walking raw rows
(ROADMAP open item 4; TiLT's compile-once/feed-deltas shape,
PAPERS.md).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from horaedb_tpu.common import Error, ReadableDuration, ensure


@dataclass
class RollupConfig:
    """Knobs for the rollup subsystem.

    Tiers: each entry is a bucket duration ("1m", "1h"); every
    registered standing query is materialized at EVERY tier.  A tier
    must evenly divide the engine's segment duration — maintenance and
    serving are segment-granular so rollup cells stay bit-identical to
    a from-raw recompute (docs/rollups.md, correctness contract).

    Specs: standing queries registered at startup, as "metric" (field
    defaults to "value") or "metric:field" strings.  More can be
    registered at runtime via POST /admin/rollups.
    """

    enabled: bool = False
    tiers: list[str] = field(default_factory=lambda: ["1m", "1h"])
    # background maintenance pass period (a write/flush also wakes it)
    roll_interval: ReadableDuration = field(
        default_factory=lambda: ReadableDuration.from_secs(2))
    # standing queries registered at engine open
    specs: list[str] = field(default_factory=list)

    def tier_millis(self) -> list[int]:
        out = []
        for t in self.tiers:
            ms = ReadableDuration.parse(t).millis
            ensure(ms > 0, f"[rollup] tier {t!r} must be positive")
            out.append(int(ms))
        ensure(len(set(out)) == len(out),
               f"[rollup] duplicate tiers: {self.tiers}")
        return out

    def spec_pairs(self) -> list[tuple[str, str]]:
        out = []
        for s in self.specs:
            ensure(isinstance(s, str) and s,
                   "[rollup] specs entries must be non-empty strings")
            metric, _, fld = s.partition(":")
            out.append((metric, fld or "value"))
        return out


def rollup_from_dict(data: dict) -> RollupConfig:
    """[rollup] TOML table -> RollupConfig (list-valued keys need their
    own handling; the generic scalar loader covers the rest)."""
    known = {"enabled", "tiers", "roll_interval", "specs"}
    unknown = set(data) - known
    if unknown:
        raise Error(f"unknown config keys for RollupConfig: "
                    f"{sorted(unknown)}")
    kwargs: dict = {}
    if "enabled" in data:
        ensure(isinstance(data["enabled"], bool),
               "[rollup] enabled expects a boolean")
        kwargs["enabled"] = data["enabled"]
    if "tiers" in data:
        ensure(isinstance(data["tiers"], list)
               and all(isinstance(t, str) for t in data["tiers"]),
               '[rollup] tiers expects a list of duration strings '
               '(e.g. ["1m", "1h"])')
        kwargs["tiers"] = list(data["tiers"])
    if "roll_interval" in data:
        v = data["roll_interval"]
        ensure(isinstance(v, str),
               '[rollup] roll_interval expects a duration string')
        kwargs["roll_interval"] = ReadableDuration.parse(v)
    if "specs" in data:
        ensure(isinstance(data["specs"], list)
               and all(isinstance(s, str) for s in data["specs"]),
               '[rollup] specs expects a list of "metric" or '
               '"metric:field" strings')
        kwargs["specs"] = list(data["specs"])
    cfg = RollupConfig(**kwargs)
    cfg.tier_millis()  # validate tier durations at load time
    cfg.spec_pairs()
    return cfg
