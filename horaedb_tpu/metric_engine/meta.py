"""Self-monitoring meta-ingest: the engine stores its own health
history (docs/observability.md, background plane).

A background loop scrapes the process's `MetricsRegistry` — every
counter, gauge and histogram sum/count the engine already maintains
(WAL backlog, cache hit rates, loop heartbeat ages, compaction counts)
— into an ordinary metrics table (default name `__meta`) THROUGH the
normal write path: WAL group commit, memtables, flush, rollups.  Each
scraped series becomes one sample of the `__meta` metric tagged
`name=<series_name>` plus the series' own labels, so operators query
the engine's health history with the engine's own query path:

    POST /query {"metric": "__meta",
                 "filters": {"name": "wal_backlog_bytes"},
                 "start": ..., "end": ..., "bucket_ms": 60000}

and — because a standing rollup is registered on `__meta` when rollups
are enabled — dashboard-shaped health queries are rollup-served like
any tenant metric.  The loop is also a standing end-to-end workload
continuously exercising ingest -> flush -> rollup, which means a broken
write path shows up as meta-ingest loop errors in /debug/tasks before
a tenant notices.

Guards (the "never recurses, never starves" contract, enforced by
tests/test_loops.py):

- the registry snapshot is taken BEFORE the write, so metrics the
  write itself bumps (wal_appends_total, memtable counters...) land in
  the NEXT scrape — a scrape can never observe, and re-write, its own
  side effects in the same pass (no meta-about-meta recursion);
- a scrape is skipped (and counted) while the previous one's write is
  still in flight — backpressure can delay health history, never queue
  an unbounded backlog of it;
- at most `max_series` samples per scrape (series are operator-bounded
  registry families, but the cap is a hard backstop), and scrapes run
  on a fixed interval — meta traffic is a small constant tax, not a
  function of tenant load.
"""

from __future__ import annotations

import asyncio
import logging
from dataclasses import dataclass, field
from typing import Optional

from horaedb_tpu.common.loops import loops
from horaedb_tpu.common.tasks import cancel_and_wait
from horaedb_tpu.common.time_ext import ReadableDuration, now_ms
from horaedb_tpu.utils import op_trace, registry

logger = logging.getLogger(__name__)

_SCRAPES = registry.counter(
    "meta_scrapes_total", "meta-ingest scrape passes written")
_SCRAPES_SKIPPED = registry.counter(
    "meta_scrapes_skipped_total",
    "meta-ingest scrapes skipped because the previous write was still "
    "in flight (backpressure guard)")
_SAMPLES_WRITTEN = registry.counter(
    "meta_samples_written_total",
    "health samples written to the meta metrics table")
_SAMPLES_DROPPED = registry.counter(
    "meta_samples_dropped_total",
    "scraped series dropped by the max_series cap or a label collision")
_SCRAPE_ERRORS = registry.counter(
    "meta_scrape_errors_total", "meta-ingest scrape passes that failed")


@dataclass
class MetaConfig:
    """[meta]: self-monitoring meta-ingest (docs/observability.md).
    Off by default — it writes real rows through the real write path,
    which is the point, but an operator should opt in."""

    enabled: bool = False
    # scrape period; also the meta loop's heartbeat period
    interval: ReadableDuration = field(
        default_factory=lambda: ReadableDuration.parse("10s"))
    # the metrics-table name health samples are written under
    metric: str = "__meta"
    # hard cap on samples per scrape (registry families are
    # operator-bounded; this is the backstop, not the budget)
    max_series: int = 4096
    # register a standing rollup on the meta metric when rollups are
    # enabled, so health dashboards are rollup-served
    rollup: bool = True


class MetaIngest:
    """Owns the scrape loop.  `scrape_once()` is the test/ops surface —
    one snapshot + one engine.write, with the recursion and
    backpressure guards applied."""

    def __init__(self, engine, config: MetaConfig, clock=now_ms):
        self._engine = engine
        self.config = config
        self._clock = clock
        self._task: Optional[asyncio.Task] = None
        self._writing = False
        self.paused = False  # bench A/B hook (config 12)

    async def start(self) -> None:
        if self.config.rollup and self._engine.rollups is not None:
            # health history serves like any tenant dashboard
            await self._engine.rollups.register(self.config.metric,
                                                "value")
        self._task = loops.spawn(
            self._loop, name="meta-ingest", owner="meta",
            period_s=self.config.interval.seconds,
            backlog=lambda: {"paused": self.paused,
                             "writing": self._writing})

    async def stop(self) -> None:
        if self._task is not None:
            await cancel_and_wait(self._task)
            self._task = None

    async def _loop(self, hb) -> None:
        interval = self.config.interval.seconds
        while True:
            await asyncio.sleep(interval)
            hb.beat()
            if self.paused:
                continue
            try:
                await self.scrape_once()
                hb.ok()
            except asyncio.CancelledError:
                raise
            except Exception as exc:  # noqa: BLE001 — retried next tick
                hb.error(exc)
                _SCRAPE_ERRORS.inc()
                logger.exception("meta-ingest scrape failed")

    def snapshot_samples(self) -> list:
        """The scrape snapshot: registry samples as engine Samples,
        capped at max_series.  A series whose own labels already carry
        a `name` key cannot be represented (the meta tag would collide)
        and is dropped + counted."""
        from horaedb_tpu.metric_engine.types import Label, Sample

        ts = int(self._clock())
        cap = self.config.max_series
        out = []
        dropped = 0
        for name, labels, value in registry.samples():
            if len(out) >= cap:
                dropped += 1
                continue
            if "name" in labels:
                dropped += 1
                continue
            try:
                v = float(value)
            except (TypeError, ValueError):
                dropped += 1
                continue
            if v != v or v in (float("inf"), float("-inf")):
                dropped += 1
                continue
            labs = sorted([Label("name", name)]
                          + [Label(k, str(lv)) for k, lv in labels.items()],
                          key=lambda l: l.name)
            out.append(Sample(name=self.config.metric, labels=labs,
                              timestamp=ts, value=v))
        if dropped:
            _SAMPLES_DROPPED.inc(dropped)
        return out

    async def scrape_once(self) -> int:
        """One scrape pass: snapshot-then-write.  Returns samples
        written (0 when skipped by the in-flight guard)."""
        if self._writing:
            # the previous pass's write hasn't finished (or something
            # re-entered us from inside the write path): skip — meta
            # traffic must never queue behind itself
            _SCRAPES_SKIPPED.inc()
            return 0
        self._writing = True
        try:
            # snapshot BEFORE writing: whatever the write bumps is next
            # pass's news, never this pass's payload (recursion guard)
            with op_trace("meta_scrape", slow_s=30.0):
                samples = self.snapshot_samples()
                if samples:
                    await self._engine.write(samples)
            _SCRAPES.inc()
            _SAMPLES_WRITTEN.inc(len(samples))
            return len(samples)
        finally:
            self._writing = False
