"""Prometheus-style metric layer over TimeMergeStorage instances
(ref: src/metric_engine + docs/rfcs/20240827-metric-engine.md).

Four index tables + one data table, each its own TimeMergeStorage with
segment-duration-implied dates (RFC:86-137); the write pipeline is
MetricManager -> IndexManager -> SampleManager (ref: metric_engine
README diagram; manager bodies are todo!() in the reference, so the
behavior here is built from the RFC)."""

from horaedb_tpu.metric_engine.types import Label, Sample, metric_id_of, series_key_of, tsid_of
from horaedb_tpu.metric_engine.engine import MetricEngine
from horaedb_tpu.metric_engine.functions import delta, increase, rate

__all__ = ["Label", "MetricEngine", "Sample", "delta", "increase",
           "metric_id_of", "rate", "series_key_of", "tsid_of"]
