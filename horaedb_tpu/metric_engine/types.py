"""Metric data model (ref: src/metric_engine/src/types.rs:17-41, RFC:34, 99).

`Sample` is the write unit and the currency between pipeline managers.
Ids are SeaHash-derived, masked to 63 bits so they remain representable
in parquet int64 statistics and the device's i64-epoch encode path.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from horaedb_tpu.common.seahash import hash64

# keep ids in i64-positive range (device + parquet friendliness)
_ID_MASK = (1 << 63) - 1

MetricId = int
SeriesId = int


@dataclass(frozen=True)
class Label:
    name: str
    value: str


@dataclass
class Sample:
    """One point: name + labels + (timestamp ms, value).

    `name_id` / `series_id` start None and are filled by MetricManager /
    IndexManager as the sample flows down the pipeline
    (ref: types.rs:25-38)."""

    name: str
    labels: list[Label]
    timestamp: int
    value: float
    name_id: Optional[MetricId] = None
    series_id: Optional[SeriesId] = None
    field_name: str = "value"


def metric_id_of(name: str) -> MetricId:
    """metric id = hash(name) (RFC:34)."""
    return hash64(name.encode()) & _ID_MASK


def field_id_of(field_name: str) -> int:
    """FieldId is u32 in the RFC's metrics table; derive it from the field
    name so distinct fields of one series never collide on the data PK."""
    return hash64(field_name.encode()) & 0x7FFF_FFFF


def series_key_of(name: str, labels: list[Label]) -> bytes:
    """Canonical series key: sorted `k=v` pairs joined by commas
    (RFC: SeriesKey = sorted TagKVs; the example renders
    {code=200, job=proxy, url=/api/put})."""
    parts = sorted(f"{l.name}={l.value}" for l in labels)
    return (name + "{" + ",".join(parts) + "}").encode()


def tsid_of(name: str, labels: list[Label]) -> SeriesId:
    """TSID = hash(sorted labels) scoped by metric name (RFC:99)."""
    return hash64(series_key_of(name, labels)) & _ID_MASK


def tsids_of_keys(keys: list[bytes]):
    """TSIDs for many canonical series keys at once: one native
    SeaHash FFI call for the whole batch (high-cardinality ingest
    hashes a key per unique series), Python spec-twin fallback.
    Returns a uint64 numpy array aligned with `keys`."""
    import numpy as np

    from horaedb_tpu import native

    h = native.seahash64_batch(keys)
    if h is None:
        h = np.fromiter((hash64(k) for k in keys), dtype=np.uint64,
                        count=len(keys))
    return h & np.uint64(_ID_MASK)
