"""MetricEngine: the manager pipeline and the five tables.

Write path (ref: metric_engine README pipeline; bodies built from RFC):
  samples -> MetricManager.populate_metric_ids
          -> IndexManager.populate_series_ids (+ index/series/tags rows)
          -> SampleManager.persist (data table rows)

Tables (RFC:106-137), each a TimeMergeStorage with the same segment
duration — the RFC's `Date` dimension is implied by the segment, so
index entries are re-registered once per (segment, series), exactly how
VictoriaMetrics scopes its inverted index by date:

  metrics {metric_name, field_name | metric_id, field_id, field_type}
  series  {metric_id, tsid | series_key}
  tags    {metric_id, tag_key, tag_value | exists}      (label_values)
  index   {metric_id, tag_key, tag_value, tsid | exists} (inverted index)
  data    {metric_id, tsid, field_id, timestamp | value}

Stage-1 divergence from the RFC, by design: data rows carry plain
(timestamp, value) columns instead of the RFC's opaque 30-minute
compressed chunks (RFC:218-231) — fixed-width columns are what the TPU
scan path wants; the chunk encoding belongs to the Append/BytesMerge
path and can layer on later without changing this API.
"""

from __future__ import annotations

import asyncio
from typing import Optional

import pyarrow as pa

from horaedb_tpu.common.error import Error, ensure
from horaedb_tpu.common.memledger import ledger as memledger
from horaedb_tpu.objstore import ObjectStore
from horaedb_tpu.ops import And, Eq, In, TimeRangePred
from horaedb_tpu.ops.downsample import ALL_AGGS
from horaedb_tpu.storage.config import StorageConfig
from horaedb_tpu.storage.read import AggregateSpec, ScanRequest
from horaedb_tpu.storage.storage import CloudObjectStorage, WriteRequest
from horaedb_tpu.storage.types import TimeRange, Timestamp
from horaedb_tpu.utils import registry, span
from horaedb_tpu.metric_engine.types import (
    Sample,
    field_id_of,
    metric_id_of,
    series_key_of,
    tsid_of,
    tsids_of_keys,
)

_TABLE_SCHEMAS = {
    "metrics": (pa.schema([
        ("metric_name", pa.string()), ("field_name", pa.string()),
        ("metric_id", pa.uint64()), ("field_id", pa.uint64()),
        ("field_type", pa.int32()),
    ]), 2),
    "series": (pa.schema([
        ("metric_id", pa.uint64()), ("tsid", pa.uint64()),
        ("series_key", pa.binary()),
    ]), 2),
    "tags": (pa.schema([
        ("metric_id", pa.uint64()), ("tag_key", pa.string()),
        ("tag_value", pa.string()), ("exists", pa.int32()),
    ]), 3),
    "index": (pa.schema([
        ("metric_id", pa.uint64()), ("tag_key", pa.string()),
        ("tag_value", pa.string()), ("tsid", pa.uint64()),
        ("exists", pa.int32()),
    ]), 4),
    "data": (pa.schema([
        ("metric_id", pa.uint64()), ("tsid", pa.uint64()),
        ("field_id", pa.uint64()), ("timestamp", pa.int64()),
        ("value", pa.float64()),
    ]), 4),
}

# chunked data table (RFC:218-231): (ts, value) pairs batch-encoded into
# opaque payloads, one row per (series, field, chunk window); Append mode
# so the BytesMerge path concatenates same-key payloads across files
_CHUNKED_DATA_SCHEMA = (pa.schema([
    ("metric_id", pa.uint64()), ("tsid", pa.uint64()),
    ("field_id", pa.uint64()), ("chunk_ts", pa.int64()),
    ("payload", pa.binary()),
]), 4)

FIELD_TYPE_FLOAT = 0
# keep per-segment registration dedup state for this many most-recently-
# USED segments (LRU): live ingest and steady backfill each keep their
# working set warm without unbounded growth
_SEEN_SEGMENTS_KEPT = 4


async def _collect(stream) -> list[pa.RecordBatch]:
    return [b async for b in stream]


def _unique_pairs(major, minor):
    """np.unique over (major, minor) int pairs, lexicographic order.

    Packs both (rebased to their minima) into ONE int64 when ranges
    allow — `np.unique(..., axis=0)` argsorts a structured view, which
    measured 2x the whole bulk-write numpy time at 2M rows; the
    structured path remains as the overflow fallback.  Returns
    (uniq_major, uniq_minor, first_index, inverse)."""
    import numpy as np

    maj = np.asarray(major).astype(np.int64, copy=False)
    mino = np.asarray(minor).astype(np.int64, copy=False)
    if len(maj) == 0:
        z = np.zeros(0, dtype=np.int64)
        return z, z, z, z
    mlo, nlo = int(maj.min()), int(mino.min())
    span = int(mino.max()) - nlo + 1
    if (int(maj.max()) - mlo + 1) * span < 2**62:
        packed = (maj - mlo) * np.int64(span) + (mino - nlo)
        u, first, inv = np.unique(packed, return_index=True,
                                  return_inverse=True)
        return u // span + mlo, u % span + nlo, first, inv
    mat = np.stack([maj, mino], axis=1)
    up, first, inv = np.unique(mat, axis=0, return_index=True,
                               return_inverse=True)
    return up[:, 0], up[:, 1], first, inv.reshape(-1)


def _empty_result() -> pa.Table:
    return pa.table({"tsid": pa.array([], type=pa.uint64()),
                     "timestamp": pa.array([], type=pa.int64()),
                     "value": pa.array([], type=pa.float64())})


class _SegmentSeen:
    """Bounded (segment -> seen keys) registration cache.  Keys are added
    only AFTER the registration write succeeds, so a failed write is
    retried on the next ingest instead of being skipped forever.

    Eviction is RECENCY-based (LRU on read AND write), not
    newest-segment-by-key: a steady backfill stream into old segments
    keeps those segments' entries alive, instead of missing the cache on
    every batch and rewriting metrics/series/index rows each time."""

    def __init__(self, keep: int = _SEEN_SEGMENTS_KEPT):
        from collections import OrderedDict

        self._by_segment: "OrderedDict[int, set]" = OrderedDict()
        self._keep = keep

    def __contains__(self, seg_key: tuple) -> bool:
        seg, key = seg_key
        entry = self._by_segment.get(seg)
        if entry is None:
            return False
        self._by_segment.move_to_end(seg)
        return key in entry

    def add(self, seg: int, key) -> None:
        if seg in self._by_segment:
            self._by_segment.move_to_end(seg)
        self._by_segment.setdefault(seg, set()).add(key)
        while len(self._by_segment) > self._keep:
            self._by_segment.popitem(last=False)


class MetricManager:
    """name -> MetricId resolution + metrics-table registration
    (ref: metric/mod.rs:25-50, body from RFC)."""

    def __init__(self, table: CloudObjectStorage, segment_ms: int):
        self.table = table
        self.segment_ms = segment_ms
        self._seen = _SegmentSeen()
        self._resolve_cache: dict[str, tuple[int, float]] = {}

    async def populate_metric_ids(self, samples: list[Sample]) -> None:
        by_seg: dict[int, dict] = {}
        for s in samples:
            s.name_id = metric_id_of(s.name)
            seg = int(Timestamp(s.timestamp).truncate_by(self.segment_ms))
            key = (s.name, s.field_name)
            if (seg, key) not in self._seen:
                by_seg.setdefault(seg, {})[key] = s.name_id
        for seg, items in by_seg.items():
            names = [k[0] for k in items]
            fnames = [k[1] for k in items]
            batch = pa.record_batch(
                [pa.array(names),
                 pa.array(fnames),
                 pa.array(list(items.values()), type=pa.uint64()),
                 pa.array([field_id_of(f) for f in fnames], type=pa.uint64()),
                 pa.array([FIELD_TYPE_FLOAT] * len(items), type=pa.int32())],
                schema=self.table.schema().user_schema)
            # registration rows cover the WHOLE segment so any query window
            # inside the segment finds them (Date == segment, RFC:104)
            await self.table.write(WriteRequest(
                batch, TimeRange.new(seg, seg + self.segment_ms)))
            # mark seen only after a durable write — retries must re-register
            for key in items:
                self._seen.add(seg, key)

    # positive name->id resolutions are cached briefly: the mapping is
    # immutable once registered, so the only staleness is a metric whose
    # data fully expired still resolving for up to the TTL — its query
    # returns empty grids either way.  Negatives are NOT cached (a
    # concurrent first write must become visible immediately).
    _RESOLVE_TTL_S = 10.0

    async def resolve(self, metric_name: str,
                      time_range: TimeRange) -> Optional[int]:
        """metric name -> id via the metrics table (cache-through)."""
        import time as _time

        now = _time.monotonic()
        hit = self._resolve_cache.get(metric_name)
        if hit is not None and hit[1] > now:
            return hit[0]
        batches = await _collect(self.table.scan(ScanRequest(
            range=time_range, predicate=Eq("metric_name", metric_name))))
        for b in batches:
            if b.num_rows:
                mid = b.column(
                    b.schema.names.index("metric_id"))[0].as_py()
                if len(self._resolve_cache) > 1024:
                    self._resolve_cache.clear()
                self._resolve_cache[metric_name] = (
                    mid, now + self._RESOLVE_TTL_S)
                return mid
        return None

    async def list_metrics(self, time_range: TimeRange) -> list[str]:
        """Distinct metric names active in the window."""
        names: set[str] = set()
        for b in await _collect(self.table.scan(ScanRequest(
                range=time_range))):
            col = b.column(b.schema.names.index("metric_name"))
            names.update(col.to_pylist())
        return sorted(names)

    async def list_fields(self, metric_name: str,
                          time_range: TimeRange) -> list[str]:
        """Distinct field names registered for a metric in the window."""
        fields: set[str] = set()
        for b in await _collect(self.table.scan(ScanRequest(
                range=time_range,
                predicate=Eq("metric_name", metric_name)))):
            col = b.column(b.schema.names.index("field_name"))
            fields.update(col.to_pylist())
        return sorted(fields)


class IndexManager:
    """TSID resolution + series/tags/index registration per segment
    (ref: index/mod.rs:25-44, body from RFC:86-137)."""

    def __init__(self, series: CloudObjectStorage, tags: CloudObjectStorage,
                 index: CloudObjectStorage, segment_ms: int):
        self.series = series
        self.tags = tags
        self.index = index
        self.segment_ms = segment_ms
        self._seen = _SegmentSeen()  # (segment, tsid)

    async def populate_series_ids(self, samples: list[Sample]) -> None:
        new: dict[int, dict[int, Sample]] = {}
        for s in samples:
            ensure(s.name_id is not None, "populate_metric_ids must run first")
            s.series_id = tsid_of(s.name, s.labels)
            seg = int(Timestamp(s.timestamp).truncate_by(self.segment_ms))
            if (seg, s.series_id) not in self._seen:
                new.setdefault(seg, {})[s.series_id] = s
        for seg, by_tsid in new.items():
            await self._register(seg, list(by_tsid.values()))
            # mark seen only after durable registration (retry on failure)
            for tsid in by_tsid:
                self._seen.add(seg, tsid)

    async def _register(self, seg: int, samples: list[Sample]) -> None:
        # whole-segment range: see MetricManager.populate_metric_ids
        rng = TimeRange.new(seg, seg + self.segment_ms)
        series_schema = self.series.schema().user_schema
        mids, tsids, keys = [], [], []
        t_mids, t_keys, t_vals = [], [], []
        i_mids, i_keys, i_vals, i_tsids = [], [], [], []
        for s in samples:
            mids.append(s.name_id)
            tsids.append(s.series_id)
            keys.append(series_key_of(s.name, s.labels))
            for lb in s.labels:
                t_mids.append(s.name_id)
                t_keys.append(lb.name)
                t_vals.append(lb.value)
                i_mids.append(s.name_id)
                i_keys.append(lb.name)
                i_vals.append(lb.value)
                i_tsids.append(s.series_id)
        await self.series.write(WriteRequest(pa.record_batch(
            [pa.array(mids, type=pa.uint64()), pa.array(tsids, type=pa.uint64()),
             pa.array(keys, type=pa.binary())], schema=series_schema), rng))
        if t_mids:
            ones = pa.array([1] * len(t_mids), type=pa.int32())
            await self.tags.write(WriteRequest(pa.record_batch(
                [pa.array(t_mids, type=pa.uint64()), pa.array(t_keys),
                 pa.array(t_vals), ones],
                schema=self.tags.schema().user_schema), rng))
            await self.index.write(WriteRequest(pa.record_batch(
                [pa.array(i_mids, type=pa.uint64()), pa.array(i_keys),
                 pa.array(i_vals), pa.array(i_tsids, type=pa.uint64()),
                 pa.array([1] * len(i_mids), type=pa.int32())],
                schema=self.index.schema().user_schema), rng))

    async def find_tsids(self, metric_id: int,
                         filters: list[tuple[str, str]],
                         time_range: TimeRange) -> Optional[set[int]]:
        """Inverted-index lookup: intersect TSID sets per label filter.
        Returns None when no filters were given (= all series)."""
        if not filters:
            return None
        result: Optional[set[int]] = None
        for key, value in filters:
            pred = And([Eq("metric_id", metric_id), Eq("tag_key", key),
                        Eq("tag_value", value)])
            tsids: set[int] = set()
            for b in await _collect(self.index.scan(ScanRequest(
                    range=time_range, predicate=pred))):
                col = b.column(b.schema.names.index("tsid"))
                tsids.update(col.to_pylist())
            result = tsids if result is None else (result & tsids)
            if not result:
                return set()
        return result

    async def label_values(self, metric_id: int, tag_key: str,
                           time_range: TimeRange) -> list[str]:
        """(RFC: tags table accelerates LabelValues)."""
        vals: set[str] = set()
        for b in await _collect(self.tags.scan(ScanRequest(
                range=time_range,
                predicate=And([Eq("metric_id", metric_id),
                               Eq("tag_key", tag_key)])))):
            col = b.column(b.schema.names.index("tag_value"))
            vals.update(col.to_pylist())
        return sorted(vals)

    async def label_names(self, metric_id: int,
                          time_range: TimeRange) -> list[str]:
        """Distinct tag keys of a metric in the window."""
        keys: set[str] = set()
        for b in await _collect(self.tags.scan(ScanRequest(
                range=time_range, predicate=Eq("metric_id", metric_id)))):
            col = b.column(b.schema.names.index("tag_key"))
            keys.update(col.to_pylist())
        return sorted(keys)

    async def resolve_series_keys(self, metric_id: int, tsids: list[int],
                                  time_range: TimeRange) -> dict[int, bytes]:
        pred = And([Eq("metric_id", metric_id),
                    In("tsid", tsids)]) if tsids else Eq("metric_id", metric_id)
        out: dict[int, bytes] = {}
        for b in await _collect(self.series.scan(ScanRequest(
                range=time_range, predicate=pred))):
            t = b.column(b.schema.names.index("tsid")).to_pylist()
            k = b.column(b.schema.names.index("series_key")).to_pylist()
            out.update(zip(t, k))
        return out


class SampleManager:
    """Data-table persistence (ref: data/mod.rs:25-44, body from RFC)."""

    def __init__(self, table: CloudObjectStorage, segment_ms: int):
        self.table = table
        self.segment_ms = segment_ms

    async def persist_chunked(self, samples: list[Sample],
                              chunk_window_ms: int) -> None:
        """Opaque-chunk layout: one row per (series, field, chunk window)
        holding the encoded (ts, value) payload (RFC:218-231)."""
        import numpy as np

        from horaedb_tpu.metric_engine import chunks

        groups: dict[tuple, list[Sample]] = {}
        for s in samples:
            ensure(s.series_id is not None, "populate_series_ids must run first")
            # trunc-toward-zero breaks the window-containment invariant
            # for pre-epoch times; chunked mode rejects them explicitly
            ensure(s.timestamp >= 0,
                   "chunked data mode requires non-negative timestamps")
            chunk_ts = int(Timestamp(s.timestamp).truncate_by(chunk_window_ms))
            groups.setdefault(
                (s.name_id, s.series_id, field_id_of(s.field_name), chunk_ts),
                []).append(s)

        by_seg: dict[int, list[tuple]] = {}
        for key, grp in groups.items():
            seg = int(Timestamp(key[3]).truncate_by(self.segment_ms))
            payload = chunks.encode_chunk(
                np.asarray([s.timestamp for s in grp], dtype=np.int64),
                np.asarray([s.value for s in grp], dtype=np.float64))
            by_seg.setdefault(seg, []).append((*key, payload))
        for seg, rows in sorted(by_seg.items()):
            # the file covers its chunk WINDOWS in full, so any query range
            # overlapping a window finds the file
            lo = min(r[3] for r in rows)
            hi = max(r[3] for r in rows) + chunk_window_ms
            batch = pa.record_batch(
                [pa.array([r[0] for r in rows], type=pa.uint64()),
                 pa.array([r[1] for r in rows], type=pa.uint64()),
                 pa.array([r[2] for r in rows], type=pa.uint64()),
                 pa.array([r[3] for r in rows], type=pa.int64()),
                 pa.array([r[4] for r in rows], type=pa.binary())],
                schema=self.table.schema().user_schema)
            await self.table.write(WriteRequest(
                batch, TimeRange.new(lo, hi)))

    async def persist(self, samples: list[Sample]) -> None:
        by_seg: dict[int, list[Sample]] = {}
        for s in samples:
            ensure(s.series_id is not None, "populate_series_ids must run first")
            seg = int(Timestamp(s.timestamp).truncate_by(self.segment_ms))
            by_seg.setdefault(seg, []).append(s)
        for seg, seg_samples in sorted(by_seg.items()):
            lo = min(s.timestamp for s in seg_samples)
            hi = max(s.timestamp for s in seg_samples)
            batch = pa.record_batch(
                [pa.array([s.name_id for s in seg_samples], type=pa.uint64()),
                 pa.array([s.series_id for s in seg_samples], type=pa.uint64()),
                 pa.array([field_id_of(s.field_name) for s in seg_samples],
                          type=pa.uint64()),
                 pa.array([s.timestamp for s in seg_samples], type=pa.int64()),
                 pa.array([s.value for s in seg_samples], type=pa.float64())],
                schema=self.table.schema().user_schema)
            await self.table.write(WriteRequest(
                batch, TimeRange.new(lo, hi + 1)))


_CHUNK_CACHE_HITS = registry.counter(
    "chunk_decode_cache_hits_total",
    "chunked-layout decode cache hits (the chunked scan cache)")
_CHUNK_CACHE_MISSES = registry.counter(
    "chunk_decode_cache_misses_total",
    "chunked-layout decode cache misses")
_CHUNK_CACHE_EVICTIONS = registry.counter(
    "chunk_decode_cache_evictions_total",
    "chunked-layout decode cache evictions")


class MetricEngine:
    """The user-facing metric API over five storage instances.

    chunked_data=True switches the data table to the RFC's opaque-chunk
    layout: (ts, value) pairs batch-encoded per (series, field, chunk
    window) with Append/BytesMerge semantics (RFC:218-231).  Better
    compression and tiny row counts; queries decode chunks on host, so
    the aggregate pushdown applies only to the row layout."""

    def __init__(self, tables: dict[str, CloudObjectStorage], segment_ms: int,
                 chunked_data: bool = False,
                 chunk_window_ms: int = 30 * 60 * 1000):
        self.tables = tables
        self.segment_ms = segment_ms
        self.chunked_data = chunked_data
        self.chunk_window_ms = chunk_window_ms
        self.metric_manager = MetricManager(tables["metrics"], segment_ms)
        self.index_manager = IndexManager(tables["series"], tables["tags"],
                                          tables["index"], segment_ms)
        self.sample_manager = SampleManager(tables["data"], segment_ms)
        # standing rollup tiers (rollup/manager.py); populated by open()
        # when a [rollup] config enables them
        self.rollups = None
        # self-monitoring meta-ingest (metric_engine/meta.py); populated
        # by open() when a [meta] config enables it
        self.meta = None
        # chunked layout: the Append-mode data table bypasses the
        # reader's scan cache (host merge, uncached), so decoded sample
        # arrays get their own byte-budgeted LRU — keyed by (predicate,
        # exact range, SST-id set) with the scan cache's structural
        # invalidation (any write/compaction changes the SST set).
        # Budget: the data-table scan-cache bytes, which chunked mode
        # otherwise leaves unused.
        if chunked_data:
            from horaedb_tpu.storage.scan_cache import ByteLRU

            self._chunk_cache = ByteLRU(
                tables["data"].reader.cache_budget_bytes,
                hits=_CHUNK_CACHE_HITS, misses=_CHUNK_CACHE_MISSES,
                evictions=_CHUNK_CACHE_EVICTIONS, trace_tier="chunk")
            # memory plane: the chunked engine's decoded-sample LRU is
            # a byte budget like any reader cache (common/memledger.py)
            self._chunk_mem_account = memledger.register(
                "chunk_cache:engine",
                lambda e: e._chunk_cache.total_bytes, anchor=self,
                kind="chunk_cache",
                budget=tables["data"].reader.cache_budget_bytes,
                owner="metric_engine")
        else:
            self._chunk_cache = None
            self._chunk_mem_account = None

    @classmethod
    async def open(cls, root_path: str, store: ObjectStore,
                   segment_ms: int = 2 * 3600 * 1000,
                   config: Optional[StorageConfig] = None,
                   chunked_data: bool = False,
                   chunk_window_ms: int = 30 * 60 * 1000,
                   wal_config=None, rollup_config=None,
                   meta_config=None, scanagent_config=None
                   ) -> "MetricEngine":
        import dataclasses

        if chunked_data:
            ensure(chunk_window_ms <= segment_ms
                   and segment_ms % chunk_window_ms == 0,
                   "chunk window must evenly divide the segment duration")
        # argument-only check, BEFORE any table/pool opens so a bad
        # combination cannot leak schedulers or worker pools: the
        # rollup maintenance/serve contract is per-cell bit equality
        # with the row-layout downsample pushdown; the chunked (Append)
        # layout has no such pushdown to mirror
        if rollup_config is not None and rollup_config.enabled:
            ensure(not chunked_data,
                   "[rollup] requires the row data layout "
                   "(chunked_data = false)")
        from horaedb_tpu.common import runtimes as runtimes_mod
        from horaedb_tpu.utils.compile_cache import enable_compile_cache

        # second process on the same machine reuses every compiled scan
        # program (the reference pays zero compile cost; we amortize ours)
        enable_compile_cache()

        tables = {}
        schemas = dict(_TABLE_SCHEMAS)
        if chunked_data:
            schemas["data"] = _CHUNKED_DATA_SCHEMA
        # one set of worker pools shared by all five tables — the
        # reference's StorageRuntimes are likewise engine-wide.  The
        # [scan] decode_workers override must be applied HERE: tables
        # receive these shared pools, so CloudObjectStorage's own
        # from_config never runs under the engine
        eng_cfg = config or StorageConfig()
        shared_runtimes = runtimes_mod.from_config(
            eng_cfg.threads, sst_override=eng_cfg.scan.decode_workers)
        wal_on = wal_config is not None and wal_config.enabled
        if wal_on:
            ensure(wal_config.dir,
                   "[wal] enabled requires wal.dir (or a Local object "
                   "store the server can derive it from)")
        try:
            for name, (schema, num_pks) in schemas.items():
                cfg = config or StorageConfig()
                if chunked_data and name == "data":
                    from horaedb_tpu.storage.config import UpdateMode

                    cfg = dataclasses.replace(cfg,
                                              update_mode=UpdateMode.APPEND)
                table = await CloudObjectStorage.open(
                    f"{root_path}/{name}", segment_ms, store, schema,
                    num_pks, cfg, runtimes=shared_runtimes)
                tables[name] = table
                if wal_on:
                    from horaedb_tpu.storage.config import UpdateMode
                    from horaedb_tpu.wal import IngestStorage

                    if table.schema().update_mode is UpdateMode.OVERWRITE:
                        import os

                        tables[name] = await IngestStorage.open(
                            table, os.path.join(wal_config.dir, name),
                            wal_config)
                    else:
                        # Append tables (the chunked data layout) have
                        # no __seq__ dedup, so replay could duplicate
                        # rows — they keep the direct write path
                        import logging as _logging

                        _logging.getLogger(__name__).info(
                            "wal: table %r is Append-mode; ingest WAL "
                            "skipped", name)
        except BaseException:
            # close whatever opened so a failed open leaks neither
            # schedulers nor worker pools
            for t in tables.values():
                await t.close()
            shared_runtimes.close()
            raise
        self = cls(tables, segment_ms, chunked_data=chunked_data,
                   chunk_window_ms=chunk_window_ms)
        self._runtimes = shared_runtimes
        if rollup_config is not None and rollup_config.enabled:
            from horaedb_tpu.rollup import RollupManager

            try:
                self.rollups = await RollupManager.open(
                    root_path, store, segment_ms, rollup_config,
                    config, shared_runtimes, tables["data"])
            except BaseException:
                await self.close()
                raise
            self.rollups.attach(self)
            # flush completions make segments rollable (wal/ingest.py)
            data = tables["data"]
            if hasattr(data, "memtable_segments"):
                data.on_flush = self.rollups.note_flush
        if meta_config is not None and meta_config.enabled:
            # self-monitoring: scrape the process's own MetricsRegistry
            # into a __meta metrics table through this engine's normal
            # write path (metric_engine/meta.py)
            from horaedb_tpu.metric_engine.meta import MetaIngest

            try:
                self.meta = MetaIngest(self, meta_config)
                await self.meta.start()
            except BaseException:
                await self.close()
                raise
        if (scanagent_config is not None and scanagent_config.active
                and not chunked_data):
            # near-data scan routing ([scanagent]): the DATA table's
            # aggregate scans — the cold dashboard path — consult the
            # shard map and route covered segments to their store-shard
            # agents (scanagent/client.py).  The index/series/tags
            # tables stay direct: their scans are row-shaped and tiny.
            from horaedb_tpu.scanagent import ScanAgentClient, ScanRouter

            try:
                self._scanagent_client = ScanAgentClient(scanagent_config)
                data = tables["data"]
                base = getattr(data, "inner", data)  # unwrap WAL front
                base.reader.scan_router = ScanRouter(
                    scanagent_config, self._scanagent_client,
                    base.root_path, base.schema().user_schema,
                    base.schema().num_primary_keys,
                    base.segment_duration_ms)
            except BaseException:
                await self.close()
                raise
        return self

    async def close(self) -> None:
        if getattr(self, "_scanagent_client", None) is not None:
            await self._scanagent_client.close()
            self._scanagent_client = None
        if self.meta is not None:
            # the meta scraper writes through this engine: stop it
            # before anything under it goes away
            await self.meta.stop()
            self.meta = None
        if self.rollups is not None:
            await self.rollups.close()
            self.rollups = None
        for t in self.tables.values():
            await t.close()
        if self._chunk_cache is not None:
            # clear-on-close: a closed engine's decoded chunks can
            # never be read again, and the ledger account goes with it
            self._chunk_cache.clear()
            memledger.deregister(self._chunk_mem_account)
            self._chunk_mem_account = None
        if getattr(self, "_runtimes", None) is not None:
            self._runtimes.close()

    async def stats(self) -> dict:
        """Data volume actually stored (rows/bytes per table, from the
        manifests) plus the ingest plane's buffered state (memtables +
        WAL backlog) — the cluster's rebalancing load signal and the
        operator's durability dashboard."""
        tables = {}
        rows = size = sst_count = 0
        mem_rows = mem_bytes = wal_backlog = 0
        last_flush_age = None
        wal_enabled = False
        for name, t in self.tables.items():
            ssts = await t.manifest.all_ssts()
            t_rows = sum(f.meta.num_rows for f in ssts)
            t_size = sum(f.meta.size for f in ssts)
            tables[name] = {"ssts": len(ssts), "rows": t_rows,
                            "bytes": t_size}
            rows += t_rows
            size += t_size
            sst_count += len(ssts)
            ingest = getattr(t, "ingest_stats", None)
            if ingest is not None:
                wal_enabled = True
                ing = ingest()
                tables[name]["ingest"] = ing
                mem_rows += ing["memtable_rows"]
                mem_bytes += ing["memtable_bytes"]
                wal_backlog += ing["wal_backlog_bytes"]
                age = ing["last_flush_age_s"]
                if age is not None and (last_flush_age is None
                                        or age > last_flush_age):
                    last_flush_age = age  # the most stale table
            # per-table cache tiers (HBM windows / host-RAM encoded
            # parts / HBM stacks) — the operator's residency dashboard
            reader = getattr(t, "reader", None)
            if reader is not None and hasattr(reader, "cache_stats"):
                tables[name]["cache"] = reader.cache_stats()
        out = {"rows": rows, "bytes": size, "ssts": sst_count,
               "tables": tables}
        cache_tables = [v["cache"] for v in tables.values()
                        if "cache" in v]
        if cache_tables:
            out["cache"] = {
                "scan_cache_bytes": sum(
                    c["scan_cache"]["bytes"] for c in cache_tables),
                "encoded_cache_bytes": sum(
                    c["encoded_cache"]["bytes"] for c in cache_tables),
                "encoded_cache_entries": sum(
                    c["encoded_cache"]["entries"] for c in cache_tables),
                "encoded_cache_hits": sum(
                    c["encoded_cache"]["hits"] for c in cache_tables),
                "encoded_cache_misses": sum(
                    c["encoded_cache"]["misses"] for c in cache_tables),
            }
        if wal_enabled:
            out["memtable_rows"] = mem_rows
            out["memtable_bytes"] = mem_bytes
            out["wal_backlog_bytes"] = wal_backlog
            out["last_flush_age_s"] = last_flush_age
        if self.rollups is not None:
            # per-rollup lag (newest raw seq vs newest rolled-up seq)
            # and segment coverage — the stale-tier alerting surface
            out["rollups"] = await self.rollups.stats()
        return out

    async def flush(self) -> dict:
        """Force-drain every WAL-fronted table's memtables to SSTs
        (POST /admin/flush).  Returns rows flushed per table."""
        out = {}
        for name, t in self.tables.items():
            flush_all = getattr(t, "flush_all", None)
            if flush_all is not None:
                out[name] = {"flushed_rows": await flush_all()}
        return out

    # ---- write ------------------------------------------------------------

    async def write(self, samples: list[Sample]) -> None:
        """The three-stage pipeline (ref: metric_engine README diagram)."""
        if not samples:
            return
        try:
            with span("engine.write", samples=len(samples)):
                await self.metric_manager.populate_metric_ids(samples)
                await self.index_manager.populate_series_ids(samples)
                if self.chunked_data:
                    await self.sample_manager.persist_chunked(
                        samples, self.chunk_window_ms)
                else:
                    await self.sample_manager.persist(samples)
        finally:
            # the delta feed, noted AFTER the writes so a maintenance
            # pass cannot consume the note while the rows are still
            # uncommitted (acked rows then get read-your-writes
            # dirtiness) — and in the finally so a PARTIALLY-failed
            # multi-segment write still dirties whatever may have
            # committed (over-dirtying is harmless, staleness is not)
            if self.rollups is not None:
                by_metric: dict[str, set] = {}
                for s in samples:
                    by_metric.setdefault(s.name, set()).add(
                        int(Timestamp(s.timestamp).truncate_by(
                            self.segment_ms)))
                self.rollups.note_write(by_metric)

    async def write_arrow(self, metric: str, tag_columns: list[str],
                          batch: pa.RecordBatch,
                          field: str = "value") -> None:
        """Vectorized bulk ingest: an Arrow batch with columns
        [*tag_columns, 'timestamp' int64, 'value' float64] for one metric.

        The scalar write() path builds a Python Sample per point; this
        path touches Python only once per UNIQUE series (for SeaHash id
        derivation and index registration) and moves the per-row work —
        series-code assignment, segment splitting, column assembly — into
        Arrow/numpy.  This is the ingest path benchmarks and remote-write
        bulk endpoints should use.
        """
        import numpy as np
        import pyarrow.compute as pc

        from horaedb_tpu.metric_engine.types import Label

        n = batch.num_rows
        if n == 0:
            return
        ensure("timestamp" in batch.schema.names
               and "value" in batch.schema.names,
               "write_arrow needs 'timestamp' and 'value' columns")
        for c in tag_columns:
            ensure(c in batch.schema.names,
                   f"write_arrow tag column {c!r} missing from batch")
            ensure(batch.column(batch.schema.names.index(c)).null_count == 0,
                   f"write_arrow tag column {c!r} contains nulls")
        # normalize idiomatic Arrow types up front (timestamp('ms') etc.)
        # so type mismatches fail here as Error, not deep in numpy
        try:
            ts_col = batch.column(
                batch.schema.names.index("timestamp")).cast(pa.int64())
            val_col = batch.column(
                batch.schema.names.index("value")).cast(pa.float64())
        except pa.ArrowInvalid as e:
            raise Error.context(
                "write_arrow timestamp/value columns must cast to "
                "int64/float64", e)
        ensure(ts_col.null_count == 0 and val_col.null_count == 0,
               "write_arrow timestamp/value columns contain nulls")

        # unique series via per-tag dictionary codes combined into one
        # composite code (Arrow C++ encodes; numpy combines); extreme
        # tag-cardinality products that would overflow the composite
        # fall back to exact row-wise unique over the code matrix
        # instead of rejecting the batch
        tag_arrays = [batch.column(batch.schema.names.index(c))
                      for c in tag_columns]
        per_tag_codes = []
        code_space = 1
        for arr in tag_arrays:
            d = pc.dictionary_encode(arr)
            d = d.combine_chunks() if isinstance(d, pa.ChunkedArray) else d
            per_tag_codes.append(np.asarray(d.indices).astype(np.int64))
            code_space *= max(1, len(d.dictionary))
        if code_space < 2**62:
            composite = np.zeros(n, dtype=np.int64)
            for c in per_tag_codes:
                card = int(c.max()) + 1 if len(c) else 1
                composite = composite * card + c
            uniq_codes, codes = np.unique(composite, return_inverse=True)
            num_series = len(uniq_codes)
        else:
            mat = np.stack(per_tag_codes, axis=1)
            uniq_rows, codes = np.unique(mat, axis=0, return_inverse=True)
            codes = codes.reshape(-1)
            num_series = len(uniq_rows)

        ts_np = ts_col.to_numpy()
        # segment assignment must match Timestamp.truncate_by (truncation
        # toward zero, not numpy floor) so pre-epoch rows land where their
        # registration does
        seg = self.segment_ms
        q = np.where(ts_np >= 0, ts_np // seg, -((-ts_np) // seg))
        seg_ids = q * seg

        # registration must happen per (segment, series) — the index is
        # Date-scoped (RFC:104), so a series spanning segments registers
        # in each one.  One Python trip per unique pair; dense per-batch
        # codes stand in for the series identity (bijective with the
        # composite/tag-row within one batch).  q is already the exact
        # segment index (seg_ids = q * seg).
        _, _, pair_rows, _ = _unique_pairs(q, codes)
        reg_samples = []
        tsid_of_code = np.full(num_series, 0, dtype=np.uint64)
        mid = metric_id_of(metric)
        series_keys = []
        code_idxes = []
        for row in pair_rows:
            row = int(row)
            labels = [Label(c, str(tag_arrays[j][row].as_py()))
                      for j, c in enumerate(tag_columns)]
            series_keys.append(series_key_of(metric, labels))
            code_idxes.append(int(codes[row]))
            reg_samples.append(Sample(metric, labels, int(ts_np[row]), 0.0,
                                      field_name=field))
        # ONE native SeaHash call for every unique series in the batch
        tsid_of_code[code_idxes] = tsids_of_keys(series_keys)
        # registration rides the scalar pipeline (per-segment dedup caches
        # make it cheap); data rows go straight to the data table
        await self.metric_manager.populate_metric_ids(reg_samples)
        await self.index_manager.populate_series_ids(reg_samples)

        val_np = val_col.to_numpy()
        tsids = tsid_of_code[codes]
        data = self.tables["data"]
        fid = field_id_of(field)
        if self.chunked_data:
            await self._write_arrow_chunked(mid, fid, codes, tsid_of_code,
                                            ts_np, val_np)
            return
        # per-segment SST writes are independent (one file + one
        # manifest delta each): overlap them with bounded concurrency so
        # a batch spanning many segments isn't serialized on parquet
        # encode round trips.  The mask is built INSIDE the permit (at
        # most 4 row masks live at once) and a TaskGroup settles every
        # sibling before a failure propagates — no write may still be
        # running after write_arrow raises.
        sem = asyncio.Semaphore(4)

        async def write_segment(seg: int) -> None:
            async with sem:
                m = seg_ids == seg
                seg_ts = ts_np[m]
                out = pa.record_batch(
                    [pa.array(np.full(int(m.sum()), mid, dtype=np.uint64)),
                     pa.array(tsids[m]),
                     pa.array(np.full(int(m.sum()), fid, dtype=np.uint64)),
                     pa.array(seg_ts, type=pa.int64()),
                     pa.array(val_np[m], type=pa.float64())],
                    schema=data.schema().user_schema)
                await data.write(WriteRequest(
                    out,
                    TimeRange.new(int(seg_ts.min()), int(seg_ts.max()) + 1)))

        try:
            if hasattr(asyncio, "TaskGroup"):  # py3.11+
                try:
                    async with asyncio.TaskGroup() as tg:
                        for seg in np.unique(seg_ids):
                            tg.create_task(write_segment(int(seg)))
                except BaseException as eg:
                    # preserve the pre-TaskGroup error surface: callers
                    # catching concrete types (Error, pa.ArrowInvalid,
                    # OSError, ...) must not be handed an
                    # ExceptionGroup; mixed-type failures still
                    # collapse to ONE exception instead of re-combining
                    # into a group.
                    if hasattr(eg, "exceptions"):
                        raise eg.exceptions[0]
                    raise
            else:
                # py3.10: no TaskGroup/ExceptionGroup.  gather with
                # return_exceptions settles EVERY sibling before the
                # first failure propagates — the same
                # no-write-still-running guarantee (leaking an
                # in-flight parquet encode past the caller corrupts
                # later work on the shared pools).
                tasks = [asyncio.ensure_future(write_segment(int(seg)))
                         for seg in np.unique(seg_ids)]
                results = await asyncio.gather(*tasks,
                                               return_exceptions=True)
                for r in results:
                    if isinstance(r, BaseException):
                        raise r
        finally:
            # noted AFTER the writes, in the finally: see write() — a
            # partially-failed batch still dirties whatever committed
            if self.rollups is not None:
                self.rollups.note_write(
                    {metric: {int(s) for s in np.unique(seg_ids)}})

    async def _write_arrow_chunked(self, mid, fid, codes, tsid_of_code,
                                   ts_np, val_np) -> None:
        """Bulk path for the chunked layout: group rows by (series, chunk
        window) in numpy, encode one payload per group."""
        import numpy as np

        from horaedb_tpu.metric_engine import chunks

        ensure(int(ts_np.min()) >= 0,
               "chunked data mode requires non-negative timestamps")
        window = self.chunk_window_ms
        chunk_idx = ts_np // window
        u_codes, u_cidx, _, inv = _unique_pairs(codes, chunk_idx)
        uniq_pairs = np.stack([u_codes, u_cidx * window], axis=1)
        order = np.argsort(inv, kind="stable")
        boundaries = np.concatenate(
            [[0], np.cumsum(np.bincount(inv, minlength=len(uniq_pairs)))])

        by_seg: dict[int, list[tuple]] = {}
        for g in range(len(uniq_pairs)):
            rows = order[boundaries[g]:boundaries[g + 1]]
            code_idx, c_ts = int(uniq_pairs[g, 0]), int(uniq_pairs[g, 1])
            payload = chunks.encode_chunk(ts_np[rows], val_np[rows])
            seg = int(Timestamp(c_ts).truncate_by(self.segment_ms))
            by_seg.setdefault(seg, []).append(
                (int(tsid_of_code[code_idx]), c_ts, payload))
        data = self.tables["data"]
        for seg, rows in sorted(by_seg.items()):
            lo = min(r[1] for r in rows)
            hi = max(r[1] for r in rows) + window
            batch = pa.record_batch(
                [pa.array(np.full(len(rows), mid, dtype=np.uint64)),
                 pa.array([r[0] for r in rows], type=pa.uint64()),
                 pa.array(np.full(len(rows), fid, dtype=np.uint64)),
                 pa.array([r[1] for r in rows], type=pa.int64()),
                 pa.array([r[2] for r in rows], type=pa.binary())],
                schema=data.schema().user_schema)
            await data.write(WriteRequest(batch, TimeRange.new(lo, hi)))

    # ---- read -------------------------------------------------------------

    async def _resolve_data_predicate(self, metric: str,
                                      filters: list[tuple[str, str]],
                                      time_range: TimeRange, field: str,
                                      ts_leaf: bool = True):
        """Shared resolve + data-table predicate construction for both
        raw and downsample queries; None means provably empty.

        `ts_leaf=False` omits the time-range leaf: bucket-ALIGNED
        downsample queries enforce [start, end) exactly through the
        aggregate grid cut, and a predicate without the range makes the
        scan-cache windows and per-window aggregation memos fully
        RANGE-INDEPENDENT — rotating/zooming dashboard queries over the
        same data share one set of cached merge windows instead of
        re-reading per range."""
        parts = await self._data_pred_parts(metric, filters, time_range,
                                            ts_leaf)
        if parts is None:
            return None
        return And([parts[0], Eq("field_id", field_id_of(field))]
                   + parts[1:])

    async def _data_pred_parts(self, metric: str,
                               filters: list[tuple[str, str]],
                               time_range: TimeRange,
                               ts_leaf: bool = True):
        """The field-independent predicate leaves (metric id, time leaf,
        tsid In) shared by single- and multi-field queries; None means
        provably empty."""
        mid = await self.metric_manager.resolve(metric, time_range)
        if mid is None:
            return None
        tsids = await self.index_manager.find_tsids(mid, filters, time_range)
        if tsids is not None and not tsids:
            return None
        preds = [Eq("metric_id", mid)]
        if self.chunked_data:
            # a chunk's row key is its window start; a window overlapping
            # the query starts at or after truncate(start, window)
            # (chunked mode stores only non-negative timestamps, so the
            # truncation is a true floor)
            lo = int(Timestamp(max(0, int(time_range.start))).truncate_by(
                self.chunk_window_ms))
            preds.append(TimeRangePred("chunk_ts", lo, int(time_range.end)))
        elif ts_leaf:
            preds.append(TimeRangePred("timestamp", int(time_range.start),
                                       int(time_range.end)))
        if tsids is not None:
            preds.append(In("tsid", sorted(tsids)))
        return preds

    async def query(self, metric: str, filters: list[tuple[str, str]],
                    time_range: TimeRange, field: str = "value") -> pa.Table:
        """Raw samples of one field of a metric matching all label filters,
        as an Arrow table (tsid, timestamp, value)."""
        with span("resolve", metric=metric):
            pred = await self._resolve_data_predicate(metric, filters,
                                                      time_range, field)
        if pred is None:
            return _empty_result()
        with span("scan", metric=metric):
            qp = await self.tables["data"].plan_query(ScanRequest(
                range=time_range, predicate=pred))
            batches = await _collect(self.tables["data"].execute_plan(qp))
        if not batches:
            return _empty_result()
        if self.chunked_data:
            with span("chunk_decode"):
                return self._decode_chunk_batches(batches, time_range)
        tbl = pa.Table.from_batches(batches)
        return tbl.select(["tsid", "timestamp", "value"])

    @staticmethod
    def _decode_chunk_arrays(batches: list[pa.RecordBatch],
                             time_range: TimeRange):
        """THE chunk-decode semantics (payload -> (tsid, ts, value)
        numpy arrays, [start, end) masked), shared by the row-table and
        device-downsample paths so they cannot drift.  Returns None when
        no samples survive the mask."""
        import numpy as np

        from horaedb_tpu import native
        from horaedb_tpu.metric_engine import chunks

        out_tsid: list[np.ndarray] = []
        out_ts: list[np.ndarray] = []
        out_val: list[np.ndarray] = []
        lo, hi = int(time_range.start), int(time_range.end)
        for b in batches:
            payload_arr = b.column(b.schema.names.index("payload"))
            # one FFI call decodes EVERY row's chunks (delta-of-delta ts,
            # XOR/scaled values, per-row dedup) — the numpy twin below
            # pays ~30 interpreter dispatches per chunk instead
            got = native.chunk_decode_batch(payload_arr)
            if got is not None:
                ts, vals, counts = got
                tsids = np.repeat(
                    b.column(b.schema.names.index("tsid")).to_numpy(
                        zero_copy_only=False), counts)
                m = (ts >= lo) & (ts < hi)
                if m.any():
                    out_ts.append(ts[m])
                    out_val.append(vals[m])
                    out_tsid.append(tsids[m])
                continue
            tsid_col = b.column(b.schema.names.index("tsid")).to_pylist()
            payloads = payload_arr.to_pylist()
            for tsid, payload in zip(tsid_col, payloads):
                ts, vals = chunks.decode_chunks(payload)
                m = (ts >= lo) & (ts < hi)
                if m.any():
                    out_ts.append(ts[m])
                    out_val.append(vals[m])
                    out_tsid.append(np.full(int(m.sum()), tsid,
                                            dtype=np.uint64))
        if not out_ts:
            return None
        return (np.concatenate(out_tsid), np.concatenate(out_ts),
                np.concatenate(out_val))

    def _decode_chunk_batches(self, batches: list[pa.RecordBatch],
                              time_range: TimeRange) -> pa.Table:
        decoded = self._decode_chunk_arrays(batches, time_range)
        if decoded is None:
            return _empty_result()
        tsid_np, ts_np, val_np = decoded
        return pa.table({
            "tsid": pa.array(tsid_np, type=pa.uint64()),
            "timestamp": pa.array(ts_np, type=pa.int64()),
            "value": pa.array(val_np, type=pa.float64()),
        })

    async def resolve_series(self, metric: str, tsids: list[int],
                             time_range: TimeRange) -> dict[int, bytes]:
        """tsid -> human-readable series key, via the series table."""
        mid = await self.metric_manager.resolve(metric, time_range)
        if mid is None:
            return {}
        return await self.index_manager.resolve_series_keys(
            mid, tsids, time_range)

    async def query_downsample(self, metric: str,
                               filters: list[tuple[str, str]],
                               time_range: TimeRange, bucket_ms: int,
                               field: str = "value",
                               aggs: tuple = ALL_AGGS,
                               use_rollup: bool = True) -> dict:
        """GROUP BY series, time(bucket) — the north-star query, executed
        as an aggregate pushdown: the data-table merge output is
        downsampled on device without ever materializing rows as Arrow.
        `aggs` restricts which aggregates are computed (count always
        rides along).  Returns {tsids, num_buckets,
        aggs: {agg -> (series, bucket) grid}}.

        When a standing rollup covers (metric, field, bucket), the grid
        is assembled from pre-aggregated tier cells plus a raw-computed
        tail for the not-yet-rolled segments — bit-identical to the
        from-raw path (docs/rollups.md).  `use_rollup=False` forces the
        raw path (the equivalence tests' recompute side).
        """
        num_buckets, aligned = self._downsample_grid(time_range, bucket_ms)
        if self.chunked_data:
            with span("downsample_chunked", metric=metric,
                      bucket_ms=bucket_ms):
                return await self._downsample_chunked(
                    metric, filters, time_range, bucket_ms, num_buckets,
                    field=field, which=tuple(aggs))
        if use_rollup:
            out, resolved = await self._try_rollup_serve(
                metric, filters, time_range, bucket_ms, num_buckets,
                field, tuple(aggs))
            if out is not None:
                return out
        else:
            resolved = None
        with span("resolve", metric=metric):
            pred = await self._resolved_or_build_predicate(
                metric, filters, time_range, field, not aligned, resolved)
        with span("downsample", metric=metric, bucket_ms=bucket_ms):
            return await self._scan_downsample(pred, time_range,
                                               bucket_ms, num_buckets,
                                               aggs)

    def _pred_from_resolved(self, resolved, field: str,
                            time_range: TimeRange, ts_leaf: bool):
        """The _data_pred_parts leaf shape, rebuilt from an
        already-resolved (mid, tsids) pair — same leaves in the same
        order, so scan-cache keys cannot drift between the paths."""
        mid, tsids = resolved
        preds = [Eq("metric_id", mid), Eq("field_id", field_id_of(field))]
        if ts_leaf:
            preds.append(TimeRangePred("timestamp", int(time_range.start),
                                       int(time_range.end)))
        if tsids is not None:
            preds.append(In("tsid", sorted(tsids)))
        return And(preds)

    async def _resolved_or_build_predicate(self, metric, filters,
                                           time_range, field: str,
                                           ts_leaf: bool, resolved):
        """Raw-path predicate, reusing the rollup probe's resolve +
        index lookup when one ran (a covered-but-lagging query must not
        pay the index resolution twice)."""
        if resolved is not None:
            return self._pred_from_resolved(resolved, field, time_range,
                                            ts_leaf)
        return await self._resolve_data_predicate(metric, filters,
                                                  time_range, field,
                                                  ts_leaf=ts_leaf)

    async def _try_rollup_serve(self, metric, filters, time_range,
                                bucket_ms: int, num_buckets: int,
                                field: str, aggs: tuple):
        """Rollup coverage check + serve.  Returns (result, resolved):
        result None means take the raw path; resolved carries the
        probe's (mid, tsids) for the raw path to reuse.  All
        rollup-tier reads route through here (the planner's coverage
        API — tools/lint.py enforces it)."""
        if self.rollups is None or not self.rollups.covers(
                metric, field, bucket_ms, time_range):
            return None, None
        with span("rollup_plan", metric=metric, bucket_ms=bucket_ms):
            mid = await self.metric_manager.resolve(metric, time_range)
            if mid is None:
                return {"tsids": [], "num_buckets": num_buckets,
                        "aggs": {}}, None
            tsids = await self.index_manager.find_tsids(mid, filters,
                                                        time_range)
            if tsids is not None and not tsids:
                return {"tsids": [], "num_buckets": num_buckets,
                        "aggs": {}}, None
        out = await self.rollups.try_serve(metric, mid, tsids, time_range,
                                           bucket_ms, field, aggs)
        return out, (mid, tsids)

    def _downsample_grid(self, time_range: TimeRange,
                         bucket_ms: int) -> tuple[int, bool]:
        """Shared bucket-grid math: (num_buckets, aligned).

        A bucket-ALIGNED range's grid cut ([0, num_buckets) on range
        -relative buckets) IS the time filter, exactly — the scan omits
        the ts leaf so cached windows/memos serve every aligned range.
        Only when the span covers at least one segment, though: there
        the read amplification is bounded by the two boundary segments
        (<= 2x), while a narrow query over a wide segment would decode
        the whole segment for a sliver (config-2 point queries keep
        their row-group pruning)."""
        span = int(time_range.end) - int(time_range.start)
        ensure(span < 2**31,
               f"query window of {span}ms exceeds the int32 offset range "
               "(~24.8 days); split the query into smaller windows")
        num_buckets = -(-span // bucket_ms)
        aligned = span % bucket_ms == 0 and span >= self.segment_ms
        return num_buckets, aligned

    async def _scan_downsample(self, pred, time_range: TimeRange,
                               bucket_ms: int, num_buckets: int,
                               aggs: tuple, top_k=None) -> dict:
        """Shared scan + result shaping for the row-layout downsample
        paths (single- and multi-field MUST stay in lockstep — parity
        -tested).  All aggregate shapes route through one QueryPlan."""
        if pred is None:
            return {"tsids": [], "num_buckets": num_buckets, "aggs": {}}
        spec = AggregateSpec(group_col="tsid", ts_col="timestamp",
                             value_col="value",
                             range_start=int(time_range.start),
                             bucket_ms=bucket_ms, num_buckets=num_buckets,
                             which=tuple(aggs))
        qp = await self.tables["data"].plan_query(
            ScanRequest(range=time_range, predicate=pred), spec=spec,
            top_k=top_k)
        group_values, grids = await self.tables["data"].execute_plan(qp)
        return {"tsids": [int(t) for t in group_values],
                "num_buckets": num_buckets,
                "aggs": grids if len(group_values) else {}}

    async def query_topk(self, metric: str,
                         filters: list[tuple[str, str]],
                         time_range: TimeRange, bucket_ms: int, k: int,
                         by: str = "max", largest: bool = True,
                         field: str = "value",
                         aggs: tuple = ALL_AGGS,
                         use_rollup: bool = True) -> dict:
        """Top-k series ranked by one aggregate over the window (BASELINE
        config 4's 'top-k hosts by max(cpu)' shape) — the downsample
        QueryPlan with a TopK stage on top.  Result rows come back best
        -first.  Row layout only (chunked tables downsample then rank
        host-side the same way)."""
        import numpy as np

        from horaedb_tpu.storage.plan import TopKSpec, apply_top_k

        ensure(by in ALL_AGGS,
               f"unknown top-k aggregate {by!r}; supported: {ALL_AGGS}")
        which = tuple(sorted(set(aggs) | {by}))
        if self.chunked_data:
            out = await self.query_downsample(metric, filters, time_range,
                                              bucket_ms, field=field,
                                              aggs=which)
            if out["tsids"]:
                values, grids = apply_top_k(
                    np.asarray(out["tsids"], dtype=np.uint64),
                    out["aggs"], TopKSpec(k=k, by=by, largest=largest))
                out["tsids"] = [int(t) for t in values]
                out["aggs"] = grids
            return out
        num_buckets, aligned = self._downsample_grid(time_range, bucket_ms)
        resolved = None
        if use_rollup:
            # a rollup-covered top-k is the covered downsample grid
            # with the TopK stage applied host-side (the chunked path's
            # shape) — same grids in, same slice out
            out, resolved = await self._try_rollup_serve(
                metric, filters, time_range, bucket_ms, num_buckets,
                field, which)
            if out is not None:
                if out["tsids"]:
                    values, grids = apply_top_k(
                        np.asarray(out["tsids"], dtype=np.uint64),
                        out["aggs"], TopKSpec(k=k, by=by, largest=largest))
                    out["tsids"] = [int(t) for t in values]
                    out["aggs"] = grids
                return out
        pred = await self._resolved_or_build_predicate(
            metric, filters, time_range, field, not aligned, resolved)
        return await self._scan_downsample(
            pred, time_range, bucket_ms, num_buckets, which,
            top_k=TopKSpec(k=k, by=by, largest=largest))

    async def query_downsample_multi(self, metric: str,
                                     filters: list[tuple[str, str]],
                                     time_range: TimeRange, bucket_ms: int,
                                     fields: list[str],
                                     aggs: tuple = ALL_AGGS,
                                     use_rollup: bool = True) -> dict:
        """GROUP BY series, time(bucket) over SEVERAL fields of one
        metric (TSBS devops queries touch up to 10 fields) with ONE
        metric/index resolve shared by every field's scan.  Returns
        {field: result}, each result shaped exactly like
        query_downsample's.

        Fields PARTITION the data table's rows (one row per sample per
        field, RFC docs/rfcs/20240827-metric-engine.md:106-137), so the
        per-field pushdown scans below each decode only their own
        field's rows — N fields cost one pass over the union, not N
        (bench config 3 reports this as the redundancy factor).  A
        shared-window variant (push In(field_id, all) once, mask each
        field post-merge) was measured 4.6x SLOWER on the host path:
        with device-layout sidecars the leaf-filtered load is cheap,
        while N masked aggregations over the UNION of rows cost N full
        passes.
        """
        ensure(len(fields) > 0, "fields must be non-empty")
        if self.chunked_data:
            return {f: await self.query_downsample(
                metric, filters, time_range, bucket_ms, field=f, aggs=aggs)
                for f in fields}
        num_buckets, aligned = self._downsample_grid(time_range, bucket_ms)
        out = {}
        remaining = list(fields)
        resolved = None
        covered = ([] if not use_rollup or self.rollups is None else
                   [f for f in remaining if self.rollups.covers(
                       metric, f, bucket_ms, time_range)])
        if covered:
            # per-field routing with ONE shared resolve: covered fields
            # read their rollup tier, the rest reuse (mid, tsids) below
            with span("rollup_plan", metric=metric, bucket_ms=bucket_ms):
                mid = await self.metric_manager.resolve(metric,
                                                        time_range)
                tsids = (None if mid is None else
                         await self.index_manager.find_tsids(
                             mid, filters, time_range))
            if mid is None or (tsids is not None and not tsids):
                return {f: {"tsids": [], "num_buckets": num_buckets,
                            "aggs": {}} for f in fields}
            resolved = (mid, tsids)
            for f in covered:
                served = await self.rollups.try_serve(
                    metric, mid, tsids, time_range, bucket_ms, f,
                    tuple(aggs))
                if served is not None:
                    out[f] = served
                    remaining.remove(f)
            if not remaining:
                return out
        parts = None
        if resolved is None:
            parts = await self._data_pred_parts(metric, filters,
                                                time_range,
                                                ts_leaf=not aligned)
        # deliberately SEQUENTIAL: each scan already pipelines its own
        # IO against pool work, and gathering all fields was measured
        # 2x slower (config 3's redundancy factor 1.4x -> 2.7x) — ten
        # interleaved merges thrash the worker pool and caches
        for f in remaining:
            if resolved is not None:
                pred = self._pred_from_resolved(resolved, f, time_range,
                                                not aligned)
            else:
                pred = (None if parts is None else
                        And([parts[0], Eq("field_id", field_id_of(f))]
                            + parts[1:]))
            out[f] = await self._scan_downsample(pred, time_range,
                                                 bucket_ms, num_buckets,
                                                 aggs)
        return out

    async def _downsample_chunked(self, metric: str, filters, time_range,
                                  bucket_ms: int, num_buckets: int,
                                  field: str = "value",
                                  which: tuple = ALL_AGGS) -> dict:
        """Chunked-layout downsample that NEVER builds an Arrow row
        table: chunk payloads batch-decode (numpy-vectorized) straight
        into the fixed-width arrays the device aggregation consumes
        (VERDICT r2 item 5; RFC 20240827:218-231 is the layout).  Same
        pushdown grids as the row layout — parity-tested.

        Repeat queries skip the (uncached Append-mode) scan AND the
        decode via the engine's decode LRU: the key is (canonical
        predicate, exact range, the data table's overlapping SST ids),
        so any write or compaction structurally invalidates, exactly
        like the row layout's scan cache.  The cached entry also memoizes
        the padded device arrays, so a repeat only re-runs the compiled
        aggregate."""
        from horaedb_tpu.ops.filter import canonical_predicate_key

        pred = await self._resolve_data_predicate(metric, filters,
                                                  time_range, field)
        if pred is None:
            return {"tsids": [], "num_buckets": num_buckets, "aggs": {}}
        key = entry = None
        if self._chunk_cache is not None:
            ssts = await self.tables["data"].manifest.find_ssts(time_range)
            key = (canonical_predicate_key(pred),
                   int(time_range.start), int(time_range.end),
                   tuple(sorted(f.id for f in ssts)))
            entry = self._chunk_cache.get(key)
        fresh = entry is None
        if fresh:
            batches = await _collect(self.tables["data"].scan(ScanRequest(
                range=time_range, predicate=pred)))
            decoded = self._decode_chunk_arrays(batches, time_range)
            if decoded is None:
                return {"tsids": [], "num_buckets": num_buckets,
                        "aggs": {}}
            entry = {"decoded": decoded, "memo": {}}
        tsid_np, ts_np, val_np = entry["decoded"]
        out = self._downsample_arrays(tsid_np, ts_np, val_np, time_range,
                                      bucket_ms, num_buckets, which=which,
                                      memo=entry["memo"])
        if fresh and key is not None:
            # charge AFTER the memo is built so the device padded
            # arrays are counted at their real size
            dev = entry["memo"].get("dev", {})
            nbytes = 24 * len(ts_np) + 1024 + sum(
                int(a.nbytes) for a in dev.values()
                if hasattr(a, "nbytes"))
            self._chunk_cache.put(key, entry, nbytes)
        return out

    @staticmethod
    def _host_bucket_grids(gid, ts_rel, vals, num_groups: int,
                           bucket_ms: int, num_buckets: int,
                           which: tuple) -> dict:
        """numpy twin of ops.downsample.time_bucket_aggregate for host
        -bound backends: accumulation cores shared with the reader's
        window partials (read.host_cell_grids), finished with the
        device path's empty-cell conventions (count 0, min +inf,
        max -inf, avg/last NaN), float32 outputs."""
        import numpy as np

        from horaedb_tpu.storage.read import host_cell_grids

        which = set(which)
        want = set(which) | ({"sum"} if "avg" in which else set())
        ncells = num_groups * num_buckets
        shape = (num_groups, num_buckets)
        cell = gid.astype(np.int64) * num_buckets + ts_rel // bucket_ms
        cores = host_cell_grids(cell, np.asarray(vals), ts_rel, ncells,
                                want)
        count = cores["count"].astype(np.float32)
        out = {"count": count.reshape(shape)}
        empty = count == 0
        if "sum" in which:
            out["sum"] = cores["sum"].astype(np.float32).reshape(shape)
        if "avg" in which:
            with np.errstate(invalid="ignore"):
                avg = np.where(empty, np.nan,
                               cores["sum"] / np.maximum(count, 1.0))
            out["avg"] = avg.astype(np.float32).reshape(shape)
        for k in ("min", "max"):
            if k in which:
                out[k] = cores[k].astype(np.float32).reshape(shape)
        if "last" in which:
            lt, li = cores["last"]
            last = np.full(ncells, np.nan)
            has = li >= 0
            last[has] = np.asarray(vals)[li[has]]
            out["last"] = last.astype(np.float32).reshape(shape)
        return out

    def _downsample_rows(self, tbl: pa.Table, time_range: TimeRange,
                         bucket_ms: int, num_buckets: int,
                         which: tuple = ALL_AGGS) -> dict:
        if tbl.num_rows == 0:
            return {"tsids": [], "num_buckets": num_buckets, "aggs": {}}
        return self._downsample_arrays(
            tbl.column("tsid").to_numpy(), tbl.column("timestamp").to_numpy(),
            tbl.column("value").to_numpy(), time_range, bucket_ms,
            num_buckets, which=which)

    def _downsample_arrays(self, tsid_np, ts_np, val_np,
                           time_range: TimeRange, bucket_ms: int,
                           num_buckets: int,
                           which: tuple = ALL_AGGS,
                           memo: Optional[dict] = None) -> dict:
        """`memo` (chunk decode cache entries pass one) holds the padded
        DEVICE arrays after the first aggregate, so repeats upload
        nothing.  Valid because the cache key pins the exact time range
        (ts offsets are range_start-relative)."""
        import numpy as np

        import jax.numpy as jnp

        from horaedb_tpu.ops.downsample import time_bucket_aggregate
        from horaedb_tpu.ops.encode import pad_capacity

        n = len(ts_np)
        dev = memo.get("dev") if memo is not None else None
        if dev is None:
            # dense group ids WITHOUT a full-length np.unique: chunk
            # decode emits long per-row runs of equal tsids, so
            # dense-ify the run VALUES (~one per chunk row) and repeat
            # the codes over run lengths — identical output to
            # np.unique(tsid_np, return_inverse=True) at a fraction of
            # the cost (the argsort of 10M u64s was the chunked cold
            # path's largest single op)
            if n:
                new_run = np.empty(n, dtype=bool)
                new_run[0] = True
                np.not_equal(tsid_np[1:], tsid_np[:-1], out=new_run[1:])
                run_idx = np.flatnonzero(new_run)
                uniq, inv = np.unique(tsid_np[run_idx],
                                      return_inverse=True)
                run_lens = np.diff(np.append(run_idx, n))
                gid = np.repeat(inv.astype(np.int32), run_lens)
            else:
                uniq = np.empty(0, dtype=np.uint64)
                gid = np.empty(0, dtype=np.int32)
            ts_rel = ts_np - int(time_range.start)
            dev = {"uniq": uniq, "gid_host": gid, "ts_rel": ts_rel,
                   "val_host": val_np}
            if memo is not None:
                memo["dev"] = dev
        uniq = dev["uniq"]
        from horaedb_tpu.storage.read import host_agg_default

        if host_agg_default():
            # numpy twin on host-bound backends (same trade-off as the
            # reader's _host_agg_ok: bincount beats XLA-CPU's segmented
            # scatters ~20x and there is no transfer to amortize)
            host = self._host_bucket_grids(dev["gid_host"], dev["ts_rel"],
                                           dev["val_host"], len(uniq),
                                           bucket_ms, num_buckets, which)
        else:
            if "ts" not in dev:
                cap = pad_capacity(n)
                pad = lambda a, d: np.pad(a.astype(d), (0, cap - n))
                dev["ts"] = jnp.asarray(pad(dev["ts_rel"], np.int32))
                dev["gid"] = jnp.asarray(pad(dev["gid_host"], np.int32))
                dev["val"] = jnp.asarray(pad(dev["val_host"], np.float32))
            aggs = time_bucket_aggregate(
                dev["ts"], dev["gid"], dev["val"],
                n, bucket_ms, num_groups=len(uniq),
                num_buckets=num_buckets, which=which)
            host = {k: np.asarray(v) for k, v in aggs.items()}
        if "last" in which:
            # match the pushdown path's grid keys (it emits last_ts only
            # alongside last): per-cell max sample time (absolute ms as
            # float, NaN for empty cells)
            gid_h, ts_rel = dev["gid_host"], dev["ts_rel"]
            cell = gid_h.astype(np.int64) * num_buckets + ts_rel // bucket_ms
            last_ts = np.full(len(uniq) * num_buckets, -np.inf)
            np.maximum.at(last_ts, cell, ts_rel.astype(np.float64))
            last_ts = last_ts.reshape(len(uniq), num_buckets)
            host["last_ts"] = np.where(np.isinf(last_ts), np.nan,
                                       last_ts + int(time_range.start))
        return {"tsids": [int(t) for t in uniq],
                "num_buckets": num_buckets, "aggs": host}

    async def label_values(self, metric: str, tag_key: str,
                           time_range: TimeRange) -> list[str]:
        mid = await self.metric_manager.resolve(metric, time_range)
        if mid is None:
            return []
        return await self.index_manager.label_values(mid, tag_key, time_range)

    async def label_names(self, metric: str,
                          time_range: TimeRange) -> list[str]:
        """Distinct tag keys of a metric in the window (Prometheus
        /api/v1/labels analogue)."""
        mid = await self.metric_manager.resolve(metric, time_range)
        if mid is None:
            return []
        return await self.index_manager.label_names(mid, time_range)

    async def list_metrics(self, time_range: TimeRange) -> list[str]:
        """Distinct metric names active in the window (Prometheus
        /api/v1/label/__name__/values analogue)."""
        return await self.metric_manager.list_metrics(time_range)

    async def list_fields(self, metric: str,
                          time_range: TimeRange) -> list[str]:
        """Distinct field names of a metric in the window."""
        return await self.metric_manager.list_fields(metric, time_range)
