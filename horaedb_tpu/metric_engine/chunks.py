"""Opaque chunk codec for the RFC's batched data design.

RFC 20240827 (data design): "Timestamp and Value are encoded by the
upper layer itself; data is batched — e.g. 30 minutes compressed into
one row", with the engine's Append/BytesMerge path concatenating chunk
payloads for the same primary key across files.

Codec (numpy-vectorized, little-endian):

    chunk := magic u8 | count u32 | ts_base i64 | ts_delta i32[count]
             | values f64[count]

Deltas are relative to ts_base (chunk windows are minutes to hours, so
int32 always fits); parquet's Snappy over the binary column compresses
the delta'd timestamps well.  A BytesMerge'd payload is a SEQUENCE of
chunks — decode_chunks walks them and concatenates.

Duplicate policy: chunks arrive in sequence order (BytesMerge
concatenates in (pk, __seq__) order), so for equal timestamps the LAST
occurrence wins — the RFC's dedup-by-seq rule applied at decode time.
"""

from __future__ import annotations

import struct

import numpy as np

from horaedb_tpu.common.error import Error, ensure

_MAGIC = 0xC7
_HEADER = struct.Struct("<BIq")  # magic u8 | count u32 | ts_base i64


def encode_chunk(ts: np.ndarray, values: np.ndarray) -> bytes:
    """Encode one chunk; ts int64 ms (any order, will be sorted),
    values float64 aligned with ts."""
    ensure(len(ts) == len(values), "ts/values length mismatch")
    ensure(len(ts) > 0, "empty chunk")
    order = np.argsort(ts, kind="stable")
    ts = np.asarray(ts, dtype=np.int64)[order]
    values = np.asarray(values, dtype=np.float64)[order]
    base = int(ts[0])
    deltas = ts - base
    ensure(int(deltas.max()) < 2**31, "chunk time span exceeds int32 deltas")
    return (_HEADER.pack(_MAGIC, len(ts), base)
            + deltas.astype(np.int32).tobytes()
            + values.tobytes())


def decode_chunks(payload: bytes) -> tuple[np.ndarray, np.ndarray]:
    """Decode a (possibly concatenated) chunk payload into
    (ts int64, values float64), sorted by ts with last-wins dedup."""
    if not payload:
        return (np.empty(0, dtype=np.int64), np.empty(0, dtype=np.float64))
    all_ts: list[np.ndarray] = []
    all_vals: list[np.ndarray] = []
    off = 0
    n = len(payload)
    while off < n:
        if off + _HEADER.size > n:
            raise Error("truncated chunk header")
        magic, count, base = _HEADER.unpack_from(payload, off)
        if magic != _MAGIC:
            raise Error(f"bad chunk magic 0x{magic:02x} at offset {off}")
        off += _HEADER.size
        need = count * (4 + 8)
        if off + need > n:
            raise Error("truncated chunk body")
        deltas = np.frombuffer(payload, dtype="<i4", count=count, offset=off)
        off += count * 4
        vals = np.frombuffer(payload, dtype="<f8", count=count, offset=off)
        off += count * 8
        all_ts.append(base + deltas.astype(np.int64))
        all_vals.append(vals)
    ts = np.concatenate(all_ts)
    vals = np.concatenate(all_vals)
    # stable sort + keep the LAST occurrence per timestamp (seq order)
    order = np.argsort(ts, kind="stable")
    ts = ts[order]
    vals = vals[order]
    keep = np.ones(len(ts), dtype=bool)
    keep[:-1] = ts[:-1] != ts[1:]
    return ts[keep], vals[keep]
