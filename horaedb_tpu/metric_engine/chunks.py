"""Opaque chunk codec for the RFC's batched data design.

RFC 20240827 (data design): "Timestamp and Value are encoded by the
upper layer itself; data is batched — e.g. 30 minutes compressed into
one row", with the engine's Append/BytesMerge path concatenating chunk
payloads for the same primary key across files.

Two codecs (numpy-vectorized, little-endian); decode dispatches on the
per-chunk magic, so mixed payloads from different builds concatenate
fine:

v1 (raw, magic 0xC7 — still decoded, no longer written):

    chunk := magic u8 | count u32 | ts_base i64 | ts_delta i32[count]
             | values f64[count]

v2 (compressed, magic 0xC8 — the default):

    chunk := magic u8 | count u32 | ts_base i64 | d1 i32
             | dod_w u8 | vmode u8 | vp1 u8 | vp2 u8 | v0 f64
             | dod i{dod_w}[count-2] | value body

Timestamps store delta-of-delta (Gorilla's model) with a PER-CHUNK byte
width: a regular scrape interval makes every dod zero, so dod_w = 0 and
the whole timestamp column costs 16 bytes regardless of count.

Values pick the smaller of two bodies per chunk:
  vmode 0 (XOR, vp1=shift vp2=width): XOR of consecutive f64 bit
    patterns (Gorilla), shifted by the chunk-wide common trailing zero
    bytes and truncated to the significant byte width —
    u{vp2}[count-1].
  vmode 1 (scaled-int delta, vp1=decimal exponent vp2=width): when
    every value is exactly v = k / 10^e for integer k, consecutive
    differences of k stored as i{vp2}[count-1].  Metrics are
    overwhelmingly integers or few-decimal gauges, whose low mantissa
    bits defeat XOR codecs; their scaled deltas fit 1-2 bytes.

Byte-granular per-chunk widths keep encode/decode as pure numpy array
ops — bit-granular Gorilla packing would force a per-value Python
loop, the opposite of this engine's design — while beating raw f64 by
>= 3x on realistic data (regular timestamps ~free, integer/decimal
gauges 1-2 bytes per value).

Duplicate policy: chunks arrive in sequence order (BytesMerge
concatenates in (pk, __seq__) order), so for equal timestamps the LAST
occurrence wins — the RFC's dedup-by-seq rule applied at decode time.
"""

from __future__ import annotations

import struct

import numpy as np

from horaedb_tpu.common.error import Error, ensure

_MAGIC_V1 = 0xC7
_HEADER_V1 = struct.Struct("<BIq")  # magic u8 | count u32 | ts_base i64
_MAGIC_V2 = 0xC8
# magic u8 | count u32 | ts_base i64 | d1 i32 | dod_w u8 | vmode u8
# | vp1 u8 | vp2 u8 | v0 f64
_HEADER_V2 = struct.Struct("<BIqiBBBBd")

_INT_DTYPES = {1: np.int8, 2: np.int16, 4: np.int32, 8: np.int64}
_VMODE_XOR = 0
_VMODE_SCALED = 1


def _int_width(m: int) -> int:
    """Smallest signed byte width holding |values| <= m."""
    return 1 if m < 2**7 else 2 if m < 2**15 else 4 if m < 2**31 else 8


def _scaled_int_body(values: np.ndarray):
    """(exponent, width, bytes) when every value is exactly k/10^e for
    int k with |k| < 2^53, else None."""
    for e in (0, 1, 2, 3, 4):
        scaled = values * (10.0 ** e)
        k = np.round(scaled)
        if np.abs(k).max(initial=0) >= 2**53:
            return None
        if not (k / (10.0 ** e) == values).all():
            continue
        deltas = np.diff(k.astype(np.int64))
        if not len(deltas):
            return e, 0, b""
        if not deltas.any():
            return e, 0, b""
        w = _int_width(int(np.abs(deltas).max()))
        return e, w, deltas.astype(_INT_DTYPES[w]).tobytes()
    return None


def _pack_low_bytes(x: np.ndarray, width: int) -> bytes:
    """Low `width` bytes of each uint64 (little-endian)."""
    if width == 0 or not len(x):
        return b""
    return np.ascontiguousarray(x, dtype="<u8").view(np.uint8) \
        .reshape(-1, 8)[:, :width].tobytes()


def _unpack_low_bytes(buf: bytes, count: int, width: int) -> np.ndarray:
    if width == 0 or count == 0:
        return np.zeros(count, dtype=np.uint64)
    raw = np.frombuffer(buf, dtype=np.uint8, count=count * width)
    out = np.zeros((count, 8), dtype=np.uint8)
    out[:, :width] = raw.reshape(count, width)
    return out.reshape(-1).view("<u8").astype(np.uint64)


def encode_chunk(ts: np.ndarray, values: np.ndarray) -> bytes:
    """Encode one chunk (v2); ts int64 ms (any order, will be sorted),
    values float64 aligned with ts."""
    ensure(len(ts) == len(values), "ts/values length mismatch")
    ensure(len(ts) > 0, "empty chunk")
    order = np.argsort(ts, kind="stable")
    ts = np.asarray(ts, dtype=np.int64)[order]
    values = np.asarray(values, dtype=np.float64)[order]
    count = len(ts)
    base = int(ts[0])
    ensure(int(ts[-1]) - base < 2**31, "chunk time span exceeds int32 deltas")

    # timestamps: delta-of-delta with per-chunk byte width
    deltas = np.diff(ts)
    d1 = int(deltas[0]) if count > 1 else 0
    dod = np.diff(deltas)  # (count-2,)
    dod_w = 0
    if len(dod) and (dod != 0).any():
        dod_w = _int_width(int(np.abs(dod).max()))
        if dod_w == 8:
            raise Error("chunk interval jump exceeds int32")
    dod_bytes = (dod.astype(_INT_DTYPES[dod_w]).tobytes() if dod_w else b"")

    # value mode 0: consecutive XOR, shifted by common trailing-zero
    # bytes, truncated to the significant byte width
    bits = values.view(np.uint64)
    xor = bits[1:] ^ bits[:-1]  # (count-1,)
    xor_shift = 0
    xor_w = 0
    nz = xor[xor != 0]
    if len(nz):
        # trailing/leading zero BYTES common to every non-zero xor
        as_bytes = np.ascontiguousarray(nz, dtype="<u8").view(np.uint8) \
            .reshape(-1, 8)
        nonzero_col = (as_bytes != 0).any(axis=0)
        cols = np.flatnonzero(nonzero_col)
        xor_shift = int(cols[0])
        xor_w = int(cols[-1]) - xor_shift + 1

    # value mode 1: exact decimal-scaled integer deltas; pick whichever
    # body is smaller
    scaled = _scaled_int_body(values)
    if scaled is not None and scaled[1] < xor_w:
        e, w, body = scaled
        vmode, vp1, vp2 = _VMODE_SCALED, e, w
    else:
        vmode, vp1, vp2 = _VMODE_XOR, xor_shift, xor_w
        body = _pack_low_bytes(xor >> np.uint64(8 * xor_shift), xor_w)

    return (_HEADER_V2.pack(_MAGIC_V2, count, base, d1, dod_w, vmode,
                            vp1, vp2, float(values[0]))
            + dod_bytes + body)


def _decode_v1(payload: bytes, off: int, n: int):
    _magic, count, base = _HEADER_V1.unpack_from(payload, off)
    off += _HEADER_V1.size
    if off + count * 12 > n:
        raise Error("truncated chunk body")
    deltas = np.frombuffer(payload, dtype="<i4", count=count, offset=off)
    off += count * 4
    vals = np.frombuffer(payload, dtype="<f8", count=count, offset=off)
    off += count * 8
    return base + deltas.astype(np.int64), np.asarray(vals), off


_MAX_CHUNK_POINTS = 1 << 27  # sanity bound; windows are minutes-hours


def _decode_v2(payload: bytes, off: int, n: int):
    if off + _HEADER_V2.size > n:
        raise Error("truncated chunk header")
    (_magic, count, base, d1, dod_w, vmode, vp1, vp2,
     v0) = _HEADER_V2.unpack_from(payload, off)
    off += _HEADER_V2.size
    # header validation: zero-width bodies legitimately carry no
    # per-point bytes (constant series at a regular interval), so a
    # corrupt count cannot be caught by body length — bound it, and
    # reject field values the encoder can never produce
    ensure(1 <= count <= _MAX_CHUNK_POINTS,
           f"implausible chunk point count {count}")
    ensure(dod_w in (0, 1, 2, 4), f"bad chunk dod width {dod_w}")
    if vmode == _VMODE_SCALED:
        ensure(vp1 <= 4 and vp2 in (0, 1, 2, 4, 8),
               f"bad scaled-int params e={vp1} w={vp2}")
    else:
        ensure(vp1 <= 7 and vp2 <= 8 and vp1 + vp2 <= 8,
               f"bad xor params shift={vp1} w={vp2}")
    n_dod = max(0, count - 2)
    n_val = max(0, count - 1)
    if off + n_dod * dod_w + n_val * vp2 > n:
        raise Error("truncated chunk body")
    if dod_w:
        dod = np.frombuffer(payload, dtype=_INT_DTYPES[dod_w], count=n_dod,
                            offset=off).astype(np.int64)
        off += n_dod * dod_w
    else:
        dod = np.zeros(n_dod, dtype=np.int64)

    ts = np.empty(count, dtype=np.int64)
    ts[0] = base
    if count > 1:
        deltas = np.empty(count - 1, dtype=np.int64)
        deltas[0] = d1
        if count > 2:
            deltas[1:] = d1 + np.cumsum(dod)
        ts[1:] = base + np.cumsum(deltas)

    if vmode == _VMODE_SCALED:
        if vp2:
            vdeltas = np.frombuffer(payload, dtype=_INT_DTYPES[vp2],
                                    count=n_val, offset=off).astype(np.int64)
            off += n_val * vp2
        else:
            vdeltas = np.zeros(n_val, dtype=np.int64)
        scale = 10.0 ** vp1
        k0 = int(np.round(v0 * scale))  # same rounding as the encoder
        ks = np.empty(count, dtype=np.int64)
        ks[0] = k0
        if count > 1:
            ks[1:] = k0 + np.cumsum(vdeltas)
        return ts, ks.astype(np.float64) / scale, off
    if vmode != _VMODE_XOR:
        raise Error(f"unknown chunk value mode {vmode}")
    xor = _unpack_low_bytes(payload[off:], n_val, vp2) \
        << np.uint64(8 * vp1)
    off += n_val * vp2
    bits = np.empty(count, dtype=np.uint64)
    bits[0] = np.array([v0], dtype="<f8").view("<u8")[0]
    if count > 1:
        bits[1:] = np.bitwise_xor.accumulate(
            np.concatenate([bits[:1], xor]))[1:]
    return ts, bits.view(np.float64), off


def decode_chunks(payload: bytes) -> tuple[np.ndarray, np.ndarray]:
    """Decode a (possibly concatenated, possibly mixed-version) chunk
    payload into (ts int64, values float64), sorted by ts with
    last-wins dedup."""
    if not payload:
        return (np.empty(0, dtype=np.int64), np.empty(0, dtype=np.float64))
    all_ts: list[np.ndarray] = []
    all_vals: list[np.ndarray] = []
    off = 0
    n = len(payload)
    while off < n:
        if off + 1 > n:
            raise Error("truncated chunk header")
        magic = payload[off]
        if magic == _MAGIC_V1:
            if off + _HEADER_V1.size > n:
                raise Error("truncated chunk header")
            ts, vals, off = _decode_v1(payload, off, n)
        elif magic == _MAGIC_V2:
            ts, vals, off = _decode_v2(payload, off, n)
        else:
            raise Error(f"bad chunk magic 0x{magic:02x} at offset {off}")
        all_ts.append(ts)
        all_vals.append(vals)
    ts = np.concatenate(all_ts)
    vals = np.concatenate(all_vals)
    # stable sort + keep the LAST occurrence per timestamp (seq order)
    order = np.argsort(ts, kind="stable")
    ts = ts[order]
    vals = vals[order]
    keep = np.ones(len(ts), dtype=bool)
    keep[:-1] = ts[:-1] != ts[1:]
    return ts[keep], vals[keep]
