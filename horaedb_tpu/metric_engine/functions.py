"""Range-vector functions over downsample grids (rate / increase / delta).

The reference's legacy architecture pushes sum/rate down from a
Prometheus Query Frontend (RFC 20220702, SURVEY.md section 5); here the
counterpart operates on the (series, bucket) grids that
query_downsample / the cluster scatter-gather return.  Pure numpy: the
grids are tiny compared to the scanned data, so this is frontend work,
not device work.

Counter semantics follow Prometheus: `increase` sums positive deltas
(counter resets — a drop in value — contribute the post-reset value),
`rate` is increase per second, `delta` is the raw last-first difference
for gauges.
"""

from __future__ import annotations

import numpy as np


def _per_bucket_last(aggs: dict) -> np.ndarray:
    return np.asarray(aggs["last"], dtype=np.float64)


def delta(aggs: dict, bucket_ms: int) -> np.ndarray:
    """Gauge delta per bucket: last(bucket) - last(previous bucket).
    First bucket and buckets following an empty bucket are NaN."""
    last = _per_bucket_last(aggs)
    out = np.full_like(last, np.nan)
    out[:, 1:] = last[:, 1:] - last[:, :-1]
    return out


def increase(aggs: dict, bucket_ms: int) -> np.ndarray:
    """Counter increase per bucket, reset-aware.

    Uses last-per-bucket samples: increase = last - prev_last, except on
    a counter reset (value dropped), where the post-reset value itself is
    the increase since the reset.  NaN where either side is empty."""
    last = _per_bucket_last(aggs)
    out = np.full_like(last, np.nan)
    prev = last[:, :-1]
    cur = last[:, 1:]
    raw = cur - prev
    # either side empty -> undefined (NaN), matching Prometheus's
    # two-sample requirement; only a genuine drop counts as a reset
    out[:, 1:] = np.where(np.isnan(prev) | np.isnan(cur), np.nan,
                          np.where(raw >= 0, raw, cur))
    return out


def rate(aggs: dict, bucket_ms: int) -> np.ndarray:
    """Counter rate per second per bucket (increase / bucket seconds)."""
    return increase(aggs, bucket_ms) / (bucket_ms / 1000.0)
