"""HTTP server (ref: src/server/src/main.rs).

Endpoints (reference parity + the query surface the reference lacks —
main.rs:59-80 notes "No query/read endpoint exists yet"):

  GET  /         hello
  GET  /toggle   pause/resume the test write-load generator
  GET  /compact  trigger compaction on every table
  GET  /metrics  Prometheus text metrics
  GET  /stats    rows/bytes per table (cluster load signal)
  POST /write    JSON samples: {"samples": [{"name", "labels": {k:v},
                 "timestamp", "value"}]}
  POST /query    JSON: {"metric", "filters": {k:v}, "start", "end",
                 optional "bucket_ms" -> downsample grid}
  GET  /label_values?metric=...&key=...&start=...&end=...
  GET  /label_names?metric=...&start=...&end=...
  GET  /metrics_list?start=...&end=...
  POST /query_arrow   like /query (raw rows) but responds Arrow IPC
  POST /write_arrow?metric=..&tags=a,b  body = Arrow IPC stream

Run: python -m horaedb_tpu.server --config docs/example.toml
"""

from __future__ import annotations

import argparse
import asyncio
import dataclasses
import logging
import math
import random
import time
from collections import deque
from typing import Optional

from aiohttp import web

from horaedb_tpu.common import Error, ensure, now_ms
from horaedb_tpu.common.deadline import (
    Deadline,
    DeadlineExceeded,
    deadline_scope,
)
from horaedb_tpu.common.deviceprof import profiler as deviceprof
from horaedb_tpu.common.loops import loops
from horaedb_tpu.common.memledger import ledger as memledger
from horaedb_tpu.common.tenant import (
    QuotaExceeded,
    TenantRegistry,
    current_tenant,
    tenant_scope,
    tenants_from_dict,
)
from horaedb_tpu.metric_engine import Label, MetricEngine, Sample
from horaedb_tpu.objstore import LocalObjectStore
from horaedb_tpu.server.config import (AdmissionConfig, ServerConfig,
                                       load_config)
from horaedb_tpu.storage.types import TimeRange
from horaedb_tpu.utils import registry, span
from horaedb_tpu.utils import tracing

logger = logging.getLogger(__name__)

# endpoints under query admission control + the query deadline; writes
# get the write deadline (and, tenants enabled, the per-tenant WAL rate
# gate) but are never queue-shed (back-pressure belongs to the storage
# write path), admin/ops endpoints run unbounded.  EVERY registered
# route must appear in exactly one of these three sets — tools/lint.py
# rejects a handler outside them, so no future endpoint can silently
# bypass the admission+tenant middleware chain.
_QUERY_ENDPOINTS = frozenset({
    "/query", "/query_arrow", "/query_topk", "/query_multi",
    "/label_values", "/label_names", "/metrics_list"})
_WRITE_ENDPOINTS = frozenset({"/write", "/write_arrow"})
_UNGOVERNED_ENDPOINTS = frozenset({
    "/", "/toggle", "/compact", "/metrics", "/stats",
    "/admin/scrub", "/admin/flush", "/admin/rollups",
    "/admin/tenants", "/admin/rebalance",
    "/debug/traces", "/debug/traces/{trace_id}", "/debug/tasks",
    "/debug/memory", "/debug/device",
    # replication ops plane (cluster/replication.py): internal
    # node-to-node shipping — the follower bounds its RPCs client-side,
    # so replication never sheds under query admission pressure
    "/repl/wal/segments", "/repl/wal/read", "/repl/wal/ack",
    "/repl/status"})

_SHED = registry.counter(
    "server_queries_shed_total",
    "queries rejected with 429 because the admission queue was full")
_QUEUE_TIMEOUTS = registry.counter(
    "server_queries_queue_timeout_total",
    "queries rejected with 503 after timing out in the admission queue")
_DEADLINE_504 = registry.counter(
    "server_requests_timed_out_total",
    "requests that exceeded their deadline and returned 504")
_ACTIVE_QUERIES = registry.gauge(
    "server_active_queries", "queries currently executing")
_QUEUED_QUERIES = registry.gauge(
    "server_queued_queries", "queries waiting for an admission slot")


class _ServiceRate:
    """Observed admission service rate: completions per second over a
    sliding window.  The denominator of the load-aware Retry-After —
    backoff guidance derived from queue depth / this rate tracks how
    overloaded the server actually is, where a constant hint tells a
    client to come back into the same collapse."""

    WINDOW_S = 30.0

    def __init__(self, clock=time.monotonic):
        self._clock = clock
        self._done: deque[float] = deque()

    def _prune(self, now: float) -> None:
        while self._done and now - self._done[0] > self.WINDOW_S:
            self._done.popleft()

    def record(self) -> None:
        now = self._clock()
        self._done.append(now)
        self._prune(now)

    def per_second(self) -> Optional[float]:
        now = self._clock()
        self._prune(now)
        if len(self._done) < 2:
            return None
        dt = now - self._done[0]
        return len(self._done) / dt if dt > 0 else None


def _load_aware_retry_after(cfg: AdmissionConfig, queued: int,
                            rate: Optional[float]) -> str:
    """Retry-After seconds for a 429/503: the estimated time to drain
    the queue ahead of a retry ((queued+1) / observed service rate),
    floored at [admission] retry_after and capped at max_retry_after.
    Falls back to the floor before any completion has been observed."""
    floor = max(1, math.ceil(cfg.retry_after.seconds))
    cap = max(floor, math.ceil(cfg.max_retry_after.seconds or 60.0))
    if not rate or rate <= 0:
        return str(floor)
    eta = (queued + 1) / rate
    return str(min(cap, max(floor, math.ceil(eta))))


class AdmissionController:
    """Semaphore-bounded query pool with a bounded FIFO wait queue
    (docs/robustness.md).  `acquire` returns "ok" (slot held — caller
    must release), "shed" (queue full: answer 429 immediately), or
    "timeout" (waited out `queue_timeout`: answer 503).  Shedding fast
    keeps latency bounded for the queries that ARE admitted instead of
    letting everyone collapse together.  This is the GLOBAL controller
    ([tenants] disabled — the pre-tenant behavior, unchanged);
    FairAdmissionController is the weighted-fair per-tenant upgrade."""

    def __init__(self, config: AdmissionConfig):
        self.config = config
        self._active = 0
        self._waiters: deque[asyncio.Future] = deque()
        self.rate = _ServiceRate()

    @property
    def active(self) -> int:
        return self._active

    @property
    def queued(self) -> int:
        return len(self._waiters)

    def _wake(self) -> None:
        while (self._waiters
               and self._active < self.config.max_concurrent_queries):
            fut = self._waiters.popleft()
            if not fut.done():  # skip cancelled (timed-out) waiters
                self._active += 1
                _ACTIVE_QUERIES.set(self._active)
                fut.set_result(True)

    async def acquire(self, timeout_s: Optional[float]) -> str:
        if self._active < self.config.max_concurrent_queries:
            self._active += 1
            _ACTIVE_QUERIES.set(self._active)
            return "ok"
        if len(self._waiters) >= self.config.max_queued:
            return "shed"
        fut = asyncio.get_running_loop().create_future()
        self._waiters.append(fut)
        _QUEUED_QUERIES.set(len(self._waiters))
        try:
            await asyncio.wait_for(fut, timeout_s)
            return "ok"
        except asyncio.TimeoutError:
            self._give_back_racing_grant(fut)
            return "timeout"
        except asyncio.CancelledError:
            # client disconnected while queued; a grant that raced the
            # cancellation must be returned or _active ratchets up
            self._give_back_racing_grant(fut)
            raise
        finally:
            try:
                self._waiters.remove(fut)
            except ValueError:
                pass  # already granted and popped by _wake
            _QUEUED_QUERIES.set(len(self._waiters))

    def _give_back_racing_grant(self, fut: asyncio.Future) -> None:
        """On py3.12+ wait_for no longer returns the result when the
        future completes in the same tick as the timeout/cancel — a
        grant from _wake (which already incremented _active) would leak
        the slot permanently.  Hand it to the next waiter instead."""
        if fut.done() and not fut.cancelled():
            self.release()

    def release(self) -> None:
        self._active -= 1
        _ACTIVE_QUERIES.set(self._active)
        self.rate.record()
        self._wake()

    def retry_after_s(self) -> str:
        return _load_aware_retry_after(self.config, self.queued,
                                       self.rate.per_second())


class _TenantQueue:
    """One tenant's admission state: its FIFO wait queue, in-flight
    count, and stride-scheduling pass value, plus the pre-bound
    per-tenant gauges."""

    __slots__ = ("tenant", "waiters", "in_flight", "pass_",
                 "active_gauge", "queued_gauge")

    def __init__(self, tenant, pass_: float):
        self.tenant = tenant
        self.waiters: deque = deque()  # (arrival_seq, future)
        self.in_flight = 0
        self.pass_ = pass_
        self.active_gauge = _ACTIVE_QUERIES.labels(tenant=tenant.name)
        self.queued_gauge = _QUEUED_QUERIES.labels(tenant=tenant.name)


class FairAdmissionController:
    """Weighted-fair admission ([tenants] enabled): the global
    [admission] slot pool is granted across PER-TENANT FIFO queues by
    stride scheduling — each grant advances the tenant's virtual
    "pass" by 1/weight, and a freed slot goes to the eligible tenant
    (non-empty queue, under its max_in_flight cap) with the LOWEST
    pass, oldest arrival breaking ties.  Tenants therefore receive
    admission slots in proportion to their weights whenever they
    contend — at any pool size, regardless of how deep a flooding
    tenant's queue is — so the flood fills only its OWN queue (429s
    scoped to it) and a compliant tenant's wait stays bounded by its
    fair share, not by the abuser's backlog.  A tenant returning from
    idle re-enters at the current virtual time (no banked priority,
    no penalty), which is what makes the discipline starvation-free
    in both directions."""

    def __init__(self, config: AdmissionConfig):
        self.config = config
        self._active = 0
        self._queues: dict[str, _TenantQueue] = {}
        self._arrivals = 0
        self._vtime = 0.0  # pass of the most recent grant
        self.rate = _ServiceRate()

    @property
    def active(self) -> int:
        return self._active

    def queued(self, tenant=None) -> int:
        if tenant is None:
            return sum(len(q.waiters) for q in self._queues.values())
        q = self._queues.get(tenant.name)
        return len(q.waiters) if q is not None else 0

    def occupancy(self) -> dict:
        """/stats: per-tenant admission occupancy."""
        return {name: {"in_flight": q.in_flight,
                       "queued": len(q.waiters)}
                for name, q in self._queues.items()
                if q.in_flight or q.waiters}

    def _q(self, tenant) -> _TenantQueue:
        q = self._queues.get(tenant.name)
        if q is None:
            q = self._queues[tenant.name] = _TenantQueue(
                tenant, pass_=self._vtime)
        elif q.tenant is not tenant:
            # a config reload re-points limits (weight/caps) without
            # disturbing in-flight state or queued waiters; gauges
            # rebind because the reload may have deregistered the old
            # children (a removed-then-recreated tenant must not write
            # into unrendered orphans)
            q.tenant = tenant
            q.active_gauge = _ACTIVE_QUERIES.labels(tenant=tenant.name)
            q.queued_gauge = _QUEUED_QUERIES.labels(tenant=tenant.name)
        if not q.waiters and q.in_flight == 0:
            # returning from idle: re-enter at the current virtual
            # time — an idle stretch must not bank priority (pass
            # frozen in the past) nor penalize (pass ahead of vtime
            # never happens; passes only advance on grants)
            q.pass_ = max(q.pass_, self._vtime)
        return q

    def _under_cap(self, q: _TenantQueue) -> bool:
        cap = q.tenant.limits.max_in_flight
        return cap <= 0 or q.in_flight < cap

    def _grant(self, q: _TenantQueue) -> None:
        q.in_flight += 1
        self._active += 1
        self._vtime = max(self._vtime, q.pass_)
        q.pass_ += 1.0 / q.tenant.limits.weight
        q.active_gauge.set(q.in_flight)
        _ACTIVE_QUERIES.set(self._active)

    async def acquire(self, tenant, timeout_s: Optional[float]) -> str:
        q = self._q(tenant)
        if (not q.waiters and self._under_cap(q)
                and self._active < self.config.max_concurrent_queries):
            self._grant(q)
            return "ok"
        # two queue bounds: the tenant's own max_queued (the scoped
        # shed that confines a flood), AND the operator's TOTAL
        # [admission] max_queued — enabling [tenants] must not quietly
        # turn an 8-entry queue bound into 64 x n_tenants of queued
        # memory and worst-case wait
        if (len(q.waiters) >= max(0, q.tenant.limits.max_queued)
                or self.queued() >= self.config.max_queued):
            return "shed"
        fut = asyncio.get_running_loop().create_future()
        self._arrivals += 1
        entry = (self._arrivals, fut)
        q.waiters.append(entry)
        q.queued_gauge.set(len(q.waiters))
        _QUEUED_QUERIES.set(self.queued())
        try:
            await asyncio.wait_for(fut, timeout_s)
            return "ok"
        except asyncio.TimeoutError:
            self._give_back_racing_grant(q, fut)
            return "timeout"
        except asyncio.CancelledError:
            self._give_back_racing_grant(q, fut)
            raise
        finally:
            try:
                q.waiters.remove(entry)
            except ValueError:
                pass  # granted and popped by _wake
            q.queued_gauge.set(len(q.waiters))
            _QUEUED_QUERIES.set(self.queued())

    def _give_back_racing_grant(self, q: _TenantQueue,
                                fut: asyncio.Future) -> None:
        # same py3.12+ race as the global controller: a grant landing
        # in the same tick as the timeout/cancel must be handed on
        if fut.done() and not fut.cancelled():
            self.release(q.tenant)

    def release(self, tenant) -> None:
        q = self._queues.get(tenant.name)
        if q is not None:
            q.in_flight -= 1
            q.active_gauge.set(q.in_flight)
        self._active -= 1
        _ACTIVE_QUERIES.set(self._active)
        self.rate.record()
        self._wake()

    def _wake(self) -> None:
        while self._active < self.config.max_concurrent_queries:
            best = None
            best_key = None
            for q in self._queues.values():
                while q.waiters and q.waiters[0][1].done():
                    # cancelled/timed-out head — acquire's finally
                    # prunes its own entry, this is just hygiene
                    q.waiters.popleft()
                if not q.waiters or not self._under_cap(q):
                    continue
                key = (q.pass_, q.waiters[0][0])
                if best_key is None or key < best_key:
                    best, best_key = q, key
            if best is None:
                break
            _seq, fut = best.waiters.popleft()
            best.queued_gauge.set(len(best.waiters))
            self._grant(best)
            fut.set_result(True)
        _QUEUED_QUERIES.set(self.queued())

    def retry_after_s(self, tenant) -> str:
        """Per-tenant backoff guidance: this tenant's queue depth over
        the GLOBAL observed service rate (a conservative ETA — the
        tenant's fair share drains at least this fast unless everyone
        else is idle)."""
        return _load_aware_retry_after(self.config, self.queued(tenant),
                                       self.rate.per_second())


class ServerState:
    def __init__(self, engine: MetricEngine, config: ServerConfig):
        self.engine = engine
        self.config = config
        self.write_enabled = True
        self.admission = AdmissionController(config.admission)
        # [tenants]: weighted-fair per-tenant admission + quotas; None
        # when disabled, and every tenant-aware path then falls back
        # to the exact pre-tenant global behavior
        self.tenants: Optional[TenantRegistry] = (
            TenantRegistry(config.tenants) if config.tenants.enabled
            else None)
        self.fair_admission: Optional[FairAdmissionController] = (
            FairAdmissionController(config.admission)
            if self.tenants is not None else None)
        # [trace] applies to the process-wide recorder (the ring and
        # slow-query log are one per process, like the registry)
        tracing.recorder.configure(
            enabled=config.trace.enabled,
            ring_size=config.trace.ring_size,
            slow_threshold_s=config.trace.slow_threshold.seconds,
            sample_rate=config.trace.sample_rate,
            op_ring_size=config.trace.op_ring_size,
            op_slow_threshold_s=config.trace.op_slow_threshold.seconds,
            op_sample_rate=config.trace.op_sample_rate)
        # [watchdog] applies to the process-wide loop registry the same
        # way (background loops registered at engine open included)
        loops.configure(
            enabled=config.watchdog.enabled,
            interval_s=config.watchdog.interval.seconds,
            stall_factor=config.watchdog.stall_factor,
            min_stall_s=config.watchdog.min_stall.seconds)
        # [memory] applies to the process-wide ledger: sampler cadence
        # + pressure watermarks (0 auto-derives from MemTotal;
        # pressure = false disables watermarks entirely)
        memledger.configure(
            enabled=config.memory.enabled,
            interval_s=config.memory.interval.seconds,
            soft_bytes=(config.memory.soft_limit.bytes
                        if config.memory.pressure else -1),
            hard_bytes=(config.memory.hard_limit.bytes
                        if config.memory.pressure else -1),
            hysteresis=config.memory.hysteresis)
        # [deviceprof] applies to the process-wide device profiler:
        # every jitted seam already routes through it (lint-enforced);
        # this sets the storm watchdog + round-timeline knobs
        deviceprof.configure(
            enabled=config.deviceprof.enabled,
            storm_window_s=config.deviceprof.storm_window.seconds,
            storm_threshold=config.deviceprof.storm_threshold,
            rounds_kept=config.deviceprof.rounds)
        # a cluster-backed server applies its [breaker] section to the
        # engine's scatter-gather policy (the setter re-points breakers
        # of already-attached remote regions too)
        if hasattr(engine, "breaker_config"):
            engine.breaker_config = config.breaker
        # [replication]: the primary-side shipping hub over this
        # engine's per-table WALs (segment listings, tail reads,
        # follower acks + the retention hook).  The lease, follower,
        # and stale-owner state wire in start_replication() — they
        # need async store I/O the constructor cannot do.
        self.repl = None
        if (config.replication.enabled
                and getattr(engine, "tables", None) is not None):
            from horaedb_tpu.cluster.replication import ReplicationHub

            self.repl = ReplicationHub(engine, config.replication)
        self.lease = None
        self.follower = None
        # [failover]: the standby's self-promotion monitor (wired in
        # start_replication when this node is a follower)
        self.monitor = None
        # set when this node lost its region's lease: governed
        # endpoints answer 409 stale-owner until a fresh lease (or
        # restart) clears it — the coordinator re-resolves and retries
        self.stale_owner: Optional[dict] = None
        self._generator_tasks: list[asyncio.Task] = []

    async def start_replication(self, store) -> None:
        """Async half of [replication] wiring: claim the configured
        region's lease (fencing every flush on this engine), and/or
        start tailing a primary into the mirror."""
        cfg = self.config.replication
        if not cfg.enabled:
            return
        from horaedb_tpu.cluster import replication as repl_mod

        # a node with a primary_url is a FOLLOWER: it must not claim
        # the region's lease at startup (that would fence the live
        # primary); promotion acquires it explicitly at failover time
        if cfg.region >= 0 and not cfg.primary_url:
            holder = cfg.holder or f"server:{self.config.port}"
            mgr = repl_mod.LeaseManager(store, "metrics")
            lease = await mgr.acquire(
                cfg.region, holder,
                ttl_ms=int(cfg.lease_ttl.seconds * 1000),
                url=self._advertise_url())
            lease.grant_ttl_ms(int(cfg.lease_ttl.seconds * 1000))
            lease.on_lost = self._on_lease_lost(cfg.region, lease)
            lease.start_renewal(cfg.renew_interval.seconds,
                                int(cfg.lease_ttl.seconds * 1000))
            repl_mod.install_fence(self.engine, lease)
            self.lease = lease
        if cfg.primary_url and cfg.mirror_dir:
            source = repl_mod.HttpWalSource(
                cfg.primary_url,
                follower_id=cfg.holder or f"server:{self.config.port}",
                timeout_s=cfg.rpc_timeout.seconds)
            self.follower = repl_mod.WalFollower(
                source, cfg.mirror_dir, cfg,
                region=cfg.region if cfg.region >= 0 else None)
            self.follower.start()
            if self.config.failover.enabled and cfg.region >= 0:
                # [failover]: this standby elects itself when the
                # primary's lease sits expired past the grace window
                self.monitor = repl_mod.StandbyMonitor(
                    self.follower,
                    repl_mod.LeaseManager(store, "metrics"),
                    cfg.region,
                    cfg.holder or f"server:{self.config.port}",
                    self.config.failover, self.config.wal,
                    lease_ttl_ms=int(cfg.lease_ttl.seconds * 1000),
                    url=self._advertise_url(),
                    on_promoted=self._on_promoted)
                self.monitor.start()
        # lease-backed routing for a cluster-backed server: the 409
        # stale-owner retry re-resolves owners from live lease records
        if (getattr(self.engine, "enable_lease_routing", None)
                is not None
                and getattr(self.engine, "owner_resolver", None) is None):
            self.engine.enable_lease_routing()

    def _advertise_url(self) -> str:
        """The address peers should resolve this node's regions to —
        stamped into lease records for lease-backed routing."""
        return f"http://127.0.0.1:{self.config.port}"

    def _on_lease_lost(self, region: int, lease):
        def on_lost(exc: BaseException) -> None:
            self.stale_owner = {
                "region": region,
                "epoch": lease.epoch,
                "reason": str(exc),
            }

        return on_lost

    async def _on_promoted(self, engine, lease) -> None:
        """StandbyMonitor takeover hook: this node IS the primary now.
        Swap the served engine (handlers read `state.engine` per
        request), start the lease heartbeat, and open a shipping hub so
        the next generation of standbys can tail us.  The pre-takeover
        engine stays open — its owner (run_server / the harness)
        closes it."""
        from horaedb_tpu.cluster.replication import ReplicationHub

        cfg = self.config.replication
        self.engine = engine
        self.lease = lease
        self.follower = None  # the monitor closed it pre-replay
        self.stale_owner = None
        lease.on_lost = self._on_lease_lost(lease.region, lease)
        lease.start_renewal(cfg.renew_interval.seconds,
                            int(cfg.lease_ttl.seconds * 1000))
        self.repl = ReplicationHub(engine, cfg)

    async def stop_replication(self) -> None:
        if self.monitor is not None:
            await self.monitor.close()
            self.monitor = None
        if self.follower is not None:
            await self.follower.close()
            self.follower = None
        if self.lease is not None:
            await self.lease.stop_renewal()
            self.lease = None
        if self.repl is not None:
            self.repl.close()
            self.repl = None

    # ---- write-load generator (ref: main.rs:187-233) ----------------------

    def start_generators(self) -> None:
        for worker in range(self.config.test.write_worker_num):
            self._generator_tasks.append(loops.spawn(
                lambda hb, w=worker: self._write_load_loop(hb, w),
                name=f"write-gen-{worker}", kind="write-gen",
                owner="test",
                period_s=self.config.test.write_interval.seconds))

    async def stop_generators(self) -> None:
        for t in self._generator_tasks:
            t.cancel()
        for t in self._generator_tasks:
            try:
                await t
            except asyncio.CancelledError:
                pass
        self._generator_tasks = []

    async def _write_load_loop(self, hb, worker: int) -> None:
        interval = self.config.test.write_interval.seconds
        rng = random.Random(worker)
        while True:
            await asyncio.sleep(interval)
            hb.beat()
            if not self.write_enabled:
                continue
            now = now_ms()
            samples = [
                Sample(name=f"bench.metric{worker}",
                       labels=[Label("host", f"host-{rng.randrange(100):03d}")],
                       timestamp=now + i % 1000, value=rng.random())
                for i in range(1000)
            ]
            try:
                await self.engine.write(samples)
                hb.ok()
            except Exception as exc:  # noqa: BLE001 — next tick retries
                hb.error(exc)
                logger.exception("write-load generator failed")


def _tracing_middleware(state: ServerState):
    """Request-scoped tracing (docs/observability.md), outermost so the
    trace sees everything including the admission wait: mint (or adopt
    from X-Trace-Id — a coordinating region already traced this
    request) a trace id for every query/write, bind the trace as
    ambient context for the handler, and on completion record it into
    the trace ring, fire the slow-query log on threshold breach or a
    504, and answer with X-Trace-Id + an X-Trace-Summary stage
    breakdown.  A downstream region also exports its recorded spans on
    X-Trace-Export so the coordinator stitches ONE distributed trace."""

    del state  # config is applied to the process-global recorder

    @web.middleware
    async def middleware(request: web.Request, handler):
        path = request.path
        if path not in _QUERY_ENDPOINTS and path not in _WRITE_ENDPOINTS:
            return await handler(request)
        incoming = request.headers.get(tracing.TRACE_HEADER)
        trace_id = incoming or tracing.new_trace_id()
        # the tenant middleware is outermost, so the ambient tenant —
        # when [tenants] is on — labels the trace root
        tenant = current_tenant()
        trace = tracing.recorder.start(
            path, trace_id=trace_id, forced=incoming is not None,
            root_fields=({"tenant": tenant.name}
                         if tenant is not None else None))
        if trace is None:
            # unsampled: the id still travels (response header +
            # downstream propagation via the ambient contextvars being
            # unset is fine — peers mint their own)
            resp = await handler(request)
            resp.headers[tracing.TRACE_HEADER] = trace_id
            return resp
        status = "ok"
        with tracing.trace_scope(trace):
            try:
                resp = await handler(request)
            except DeadlineExceeded:
                tracing.recorder.finish(trace, status="timeout")
                raise
            except Exception:
                tracing.recorder.finish(trace, status="error")
                raise
        if resp.status == 504:
            status = "timeout"
        elif resp.status >= 400:
            status = "error"
        done = tracing.recorder.finish(trace, status=status)
        resp.headers[tracing.TRACE_HEADER] = trace.trace_id
        resp.headers["X-Trace-Summary"] = tracing.summarize(done)
        if incoming is not None:
            # we are a downstream region of a traced request: hand our
            # spans back for stitching
            resp.headers[tracing.EXPORT_HEADER] = tracing.export_payload(done)
        return resp

    return middleware


def _tenant_middleware(state: ServerState):
    """Tenant identity at ingress (docs/robustness.md, tenant
    isolation): resolve the X-Tenant header (absent -> the "default"
    tenant) against the [tenants] registry and bind the tenant as
    ambient context for everything below — the trace root, weighted
    -fair admission, the scan-byte budget's checkpoint hook, and the
    WAL rate gate all read it from the contextvar.  A no-op when
    [tenants] is disabled (the registry is None), so the pre-tenant
    request path is byte-for-byte unchanged."""

    @web.middleware
    async def middleware(request: web.Request, handler):
        reg = state.tenants
        path = request.path
        if reg is None or (path not in _QUERY_ENDPOINTS
                           and path not in _WRITE_ENDPOINTS):
            return await handler(request)
        try:
            tenant = reg.resolve(request.headers.get("X-Tenant"))
        except Error as e:
            return web.json_response({"error": str(e)}, status=400)
        # cluster-tier weight forwarding (cluster/remote.py): a peer
        # coordinator sends the tenant's node-tier weight alongside
        # X-Tenant so our fair scheduler grants the same share.  Only
        # AUTO-minted tenants accept it — a configured tenant's weight
        # is this node's policy, and the shared default tenant must
        # never be re-weighted by one caller for everyone
        fwd = request.headers.get("X-Tenant-Weight")
        if fwd is not None and tenant.auto:
            try:
                w = float(fwd)
            except ValueError:
                w = 0.0
            if 0.0 < w <= 1e6 and tenant.limits.weight != w:
                tenant.limits = dataclasses.replace(
                    tenant.limits, weight=w)
        t0 = time.perf_counter()
        try:
            with tenant_scope(tenant):
                return await handler(request)
        finally:
            # server-side per-tenant latency (quantiles on /stats);
            # sheds and 504s count — a tenant's experienced latency
            # includes its rejections
            tenant.query_seconds.observe(time.perf_counter() - t0)

    return middleware


def _resilience_middleware(state: ServerState):
    """Request-lifecycle robustness (docs/robustness.md): mint ONE
    Deadline per request at ingress (per-endpoint default, shrinkable
    via X-Deadline-Ms header or timeout_ms param), bind it as the
    ambient deadline every layer below budgets against, enforce it with
    a hard 504 backstop, and run query endpoints through admission
    control (429 queue-full shed / 503 queued-wait timeout, both with
    a LOAD-AWARE Retry-After derived from queue depth and the observed
    service rate).  An already-expired deadline fast-fails 504 BEFORE
    consuming an admission slot, and one that expires while queued
    answers 504 without ever holding a slot — dead requests must not
    occupy queue capacity under overload.  With [tenants] enabled,
    admission is weighted-fair over per-tenant queues and quota
    breaches (QuotaExceeded from the scan/WAL budgets) map to 429s
    scoped to the offending tenant."""

    def _labeled(counter, tenant):
        return (counter.labels(tenant=tenant.name)
                if tenant is not None else counter)

    def _timeout_504(timeout_s, tenant):
        _labeled(_DEADLINE_504, tenant).inc()
        return web.json_response(
            {"error": f"deadline exceeded ({timeout_s:.3f}s budget)"},
            status=504)

    def _quota_429(exc: QuotaExceeded, tenant):
        if tenant is not None:
            tenant.quota_rejected(exc.resource)
        return web.json_response(
            {"error": str(exc), "quota": exc.resource,
             "tenant": exc.tenant},
            status=429,
            headers={"Retry-After":
                     str(max(1, math.ceil(exc.retry_after_s)))})

    @web.middleware
    async def middleware(request: web.Request, handler):
        cfg = state.config.admission
        path = request.path
        is_query = path in _QUERY_ENDPOINTS
        is_write = path in _WRITE_ENDPOINTS
        if (is_query or is_write) and state.stale_owner is not None:
            # this node lost its region's lease mid-failover: refuse
            # data-plane traffic with 409 so the coordinator
            # re-resolves ownership and retries against the new
            # primary (cluster/replication.py StaleOwnerError)
            return web.json_response(
                {"error": "stale owner: this node's region lease was "
                          "lost", **state.stale_owner},
                status=409)
        if is_query:
            default_s = cfg.query_timeout.seconds or None
        elif is_write:
            default_s = cfg.write_timeout.seconds or None
        else:
            default_s = None  # ops/admin endpoints run unbounded
        timeout_s = default_s
        tenant = current_tenant()  # bound by the tenant middleware
        raw = (request.headers.get("X-Deadline-Ms")
               or request.query.get("timeout_ms"))
        if raw is not None:
            try:
                asked_s = int(raw) / 1000.0
            except ValueError:
                return web.json_response(
                    {"error": f"bad deadline: {raw!r}"}, status=400)
            if asked_s <= 0 and (is_query or is_write):
                # dead on arrival: the client declared its budget
                # already spent — 504 before any slot, queue entry,
                # WAL frame, or fsync is consumed
                _labeled(_DEADLINE_504, tenant).inc()
                return web.json_response(
                    {"error": "deadline exceeded (budget spent before "
                              "arrival)"}, status=504)
            cap = cfg.max_timeout.seconds or None
            timeout_s = max(0.001, min(asked_s, cap) if cap else asked_s)
        if (tenant is not None and (is_query or is_write)
                and tenant.limits.max_query_time.seconds > 0):
            # operator-side per-tenant deadline cap: a no-SLO class
            # cannot hold server time past its envelope, whatever the
            # client asked for
            tcap = tenant.limits.max_query_time.seconds
            timeout_s = tcap if timeout_s is None else min(timeout_s,
                                                           tcap)
        deadline = (Deadline.after(timeout_s, reason=path)
                    if timeout_s is not None else None)
        fair = state.fair_admission if tenant is not None else None
        # fast-fail: a request that arrives already out of time is
        # answered 504 here, before it can consume an admission slot
        # or queue capacity
        if ((is_query or is_write) and deadline is not None
                and deadline.remaining() <= 0.0):
            return _timeout_504(timeout_s, tenant)
        admitted = False
        try:
            if cfg.enabled and is_query:
                wait_s = cfg.queue_timeout.seconds
                if deadline is not None:
                    wait_s = deadline.budget(wait_s)
                with span("admission_wait",
                          queued=(fair.queued(tenant)
                                  if fair is not None
                                  else state.admission.queued)):
                    if fair is not None:
                        outcome = await fair.acquire(tenant, wait_s)
                    else:
                        outcome = await state.admission.acquire(wait_s)
                if (outcome == "ok" and deadline is not None
                        and deadline.expired):
                    # the grant raced the expiry: give the slot back —
                    # a dead request must not occupy it
                    if fair is not None:
                        fair.release(tenant)
                    else:
                        state.admission.release()
                    return _timeout_504(timeout_s, tenant)
                if outcome == "shed":
                    _labeled(_SHED, tenant).inc()
                    retry = (fair.retry_after_s(tenant)
                             if fair is not None
                             else state.admission.retry_after_s())
                    scope = (f" for tenant {tenant.name!r}"
                             if tenant is not None else "")
                    return web.json_response(
                        {"error": "overloaded: admission queue full"
                                  + scope},
                        status=429, headers={"Retry-After": retry})
                if outcome == "timeout":
                    if deadline is not None and deadline.expired:
                        # expired while queued: the request is dead —
                        # 504, and it never held a slot
                        return _timeout_504(timeout_s, tenant)
                    _labeled(_QUEUE_TIMEOUTS, tenant).inc()
                    retry = (fair.retry_after_s(tenant)
                             if fair is not None
                             else state.admission.retry_after_s())
                    return web.json_response(
                        {"error": "overloaded: timed out waiting for a "
                                  "query slot"},
                        status=503, headers={"Retry-After": retry})
                admitted = True
            with deadline_scope(deadline):
                try:
                    if deadline is None or is_write:
                        # writes are deadline-SCOPED (each outgoing RPC
                        # budgets against it) but never hard-cancelled:
                        # aborting a multi-region commit mid-flight
                        # would break the write path's no-partial-commit
                        # retry-safety discipline
                        return await handler(request)
                    # queries are idempotent: hard backstop around the
                    # cooperative checkpoints — even a handler that
                    # never checkpoints cannot overrun its deadline
                    return await asyncio.wait_for(handler(request),
                                                  deadline.remaining())
                except QuotaExceeded as exc:
                    # a per-tenant resource budget fired (scan bytes at
                    # a checkpoint, WAL rate ahead of group commit):
                    # 429 scoped to the tenant, Retry-After from the
                    # bucket's actual deficit
                    return _quota_429(exc, tenant)
                except (asyncio.TimeoutError, DeadlineExceeded):
                    if deadline is None:
                        raise  # not ours: no deadline was bound
                    deadline.cancel()
                    return _timeout_504(timeout_s, tenant)
        finally:
            if admitted:
                if fair is not None:
                    fair.release(tenant)
                else:
                    state.admission.release()

    return middleware


def _tenant_stats(state: ServerState) -> dict:
    """Per-tenant isolation state: quotas, server-side latency
    quantiles, and live admission occupancy — the shared body of the
    /stats `tenants` section and GET /admin/tenants."""
    tstats = state.tenants.stats()
    for name, occ in state.fair_admission.occupancy().items():
        tstats.setdefault(name, {}).update(occ)
    return tstats


def build_app(state: ServerState) -> web.Application:
    routes = web.RouteTableDef()

    def _error_response(e: Error) -> web.Response:
        """Client-error mapping shared by every handler.  Request
        -deadline expiry re-raises so the middleware answers 504; a
        STORAGE-side deadline overrun (objstore retry middleware's
        per-op deadline) is the server's problem, not the client's —
        503, never 400."""
        from horaedb_tpu.objstore.middleware import DeadlineExceededError

        if isinstance(e, (DeadlineExceeded, QuotaExceeded)):
            # the resilience middleware owns these mappings (504 and
            # the tenant-scoped quota 429 respectively)
            raise e
        if isinstance(e, DeadlineExceededError):
            return web.json_response({"error": str(e)}, status=503)
        return web.json_response({"error": str(e)}, status=400)

    def _attach_partial(body: dict, meta) -> dict:
        """Degraded scatter-gather marker on /query* JSON bodies (meta
        is None for single-engine servers — shape unchanged)."""
        if meta is not None:
            body["partial"] = meta.partial
            body["missing_regions"] = meta.missing_regions
        return body

    def _partial_headers(meta) -> dict:
        """The same marker for Arrow responses, as HTTP headers (the
        IPC stream body stays pure data)."""
        if meta is None:
            return {}
        headers = {"X-Partial": "true" if meta.partial else "false"}
        if meta.missing_regions:
            headers["X-Missing-Regions"] = ",".join(
                str(r) for r in meta.missing_regions)
        return headers

    async def _engine_query(metric, filters, rng, field):
        """Row query with degraded gather when the engine is a Cluster
        (returns (table, GatherMeta|None))."""
        gather = getattr(state.engine, "query_gather", None)
        if gather is not None:
            return await gather(metric, filters, rng, field=field)
        tbl = await state.engine.query(metric, filters, rng, field=field)
        return tbl, None

    async def _engine_downsample(metric, filters, rng, bucket_ms, field):
        gather = getattr(state.engine, "query_downsample_gather", None)
        if gather is not None:
            return await gather(metric, filters, rng, bucket_ms,
                                field=field)
        out = await state.engine.query_downsample(metric, filters, rng,
                                                  bucket_ms, field=field)
        return out, None

    @routes.get("/")
    async def hello(_req: web.Request) -> web.Response:
        return web.Response(text="Hello, horaedb-tpu!")

    @routes.get("/toggle")
    async def toggle(_req: web.Request) -> web.Response:
        state.write_enabled = not state.write_enabled
        return web.Response(text=f"write_enabled={state.write_enabled}")

    @routes.get("/compact")
    async def compact(_req: web.Request) -> web.Response:
        tables = getattr(state.engine, "tables", None)
        if tables is None:
            return web.json_response(
                {"error": "compaction is a per-node operation; this "
                          "server fronts a cluster — compact each "
                          "region's own server"}, status=501)
        for table in tables.values():
            await table.compact()
        rollups = getattr(state.engine, "rollups", None)
        if rollups is not None:
            for table in rollups.tiers.values():
                await table.compact()
        return web.Response(text="compaction triggered")

    @routes.get("/metrics")
    async def metrics(_req: web.Request) -> web.Response:
        return web.Response(text=registry.render(),
                            content_type="text/plain")

    @routes.post("/admin/scrub")
    async def admin_scrub(req: web.Request) -> web.Response:
        """On-demand orphan scrub across every table (storage/gc.py).
        Optional ?grace_ms= overrides the configured grace period for
        this pass only (grace_ms=0 reclaims everything currently
        observed as orphaned — operator big-hammer, use with care)."""
        grace_s = None
        raw = req.query.get("grace_ms")
        if raw is not None:
            try:
                grace_s = int(raw) / 1000.0
            except ValueError:
                return web.json_response(
                    {"error": f"bad grace_ms: {raw!r}"}, status=400)
        tables = getattr(state.engine, "tables", None)
        if tables is None:
            # cluster-backed servers have no direct table surface;
            # scrub each region's node instead
            return web.json_response(
                {"error": "scrub is a per-node operation; this server "
                          "fronts a cluster — scrub each region's own "
                          "server"}, status=501)
        out = {}
        for name, table in tables.items():
            report = await table.scrub(grace_override_s=grace_s)
            out[name] = report.as_dict()
        rollups = getattr(state.engine, "rollups", None)
        if rollups is not None:
            for tier_ms, table in rollups.tiers.items():
                report = await table.scrub(grace_override_s=grace_s)
                out[f"rollup_{rollups.tier_names[tier_ms]}"] = \
                    report.as_dict()
        return web.json_response(out)

    @routes.get("/debug/traces")
    async def debug_traces(req: web.Request) -> web.Response:
        """Newest-first summaries of recently completed traces
        (?limit=N, default 50; docs/observability.md).  ?kind=query|op
        restricts to one trace population (default: both, merged);
        ?op=<name> to one background op (compaction, flush, wal_commit,
        rollup_pass, scrub, health_round, meta_scrape — implies
        kind=op)."""
        try:
            limit = int(req.query.get("limit", "50"))
        except ValueError:
            return web.json_response(
                {"error": f"bad limit: {req.query.get('limit')!r}"},
                status=400)
        kind = req.query.get("kind", "all")
        if kind not in ("all", "query", "op"):
            return web.json_response(
                {"error": f"bad kind: {kind!r} (query|op|all)"},
                status=400)
        op = req.query.get("op")
        return web.json_response(
            {"traces": tracing.recorder.list(limit, kind=kind, op=op)})

    @routes.get("/debug/tasks")
    async def debug_tasks(_req: web.Request) -> web.Response:
        """The background-loop registry (common/loops.py): every loop's
        liveness, heartbeat age, stall flag, last success, consecutive
        errors + last error, and backlog hints (WAL backlog bytes,
        dirty rollup segments, pending compaction tasks).  This is the
        maintenance plane's /debug/traces."""
        return web.json_response({
            "loops": loops.snapshot(),
            "watchdog": {
                "enabled": loops.enabled,
                "interval_s": loops.interval_s,
                "stall_factor": loops.stall_factor,
                "min_stall_s": loops.min_stall_s,
            },
        })

    @routes.get("/debug/memory")
    async def debug_memory(_req: web.Request) -> web.Response:
        """The memory ledger (common/memledger.py): the full account
        tree (bytes/budget/utilization/high-water per kind, instance
        detail), RSS, unattributed = RSS - Σ accounts (leaks positive,
        double counting negative), pressure watermark state, and
        per-device accelerator bytes where the backend reports them.
        This is the byte-plane twin of /debug/tasks."""
        return web.json_response(memledger.snapshot())

    @routes.get("/debug/device")
    async def debug_device(_req: web.Request) -> web.Response:
        """The device plane (common/deviceprof.py): the compile-cache
        table (per-fn compile counts/seconds, last cache key, storm
        state), dispatch/exec time split, h2d/d2h transfer totals, the
        mesh round timeline (slot fill, padding waste, per-shard row
        imbalance), and per-device memory with high-water marks.  This
        is the jit seam's /debug/memory."""
        out = deviceprof.snapshot()
        sample = memledger.sample_once()
        out["devices"] = sample.get("devices", [])
        return web.json_response(out)

    @routes.get("/debug/traces/{trace_id}")
    async def debug_trace(req: web.Request) -> web.Response:
        """One trace as a JSON span tree: per-stage durations, cache
        tier hits, object-store GETs/bytes — stitched across regions
        when the query scatter-gathered."""
        trace_id = req.match_info["trace_id"]
        d = tracing.recorder.get(trace_id)
        if d is None:
            return web.json_response(
                {"error": f"trace {trace_id!r} not in the ring (expired "
                          "or never sampled)"}, status=404)
        out = tracing.span_tree(d)
        out["summary"] = tracing.summarize(d)
        return web.json_response(out)

    @routes.get("/stats")
    async def stats(_req: web.Request) -> web.Response:
        # data-volume load signal for cluster rebalancing (rows/bytes/
        # SSTs per table from the manifests) + the ingest plane's
        # buffered state (memtable rows/bytes, WAL backlog, flush age)
        # + the maintenance plane's health rollup (stalled/erroring
        # loops — degraded maintenance surfaces BEFORE query latency)
        out = await state.engine.stats()
        out["loops"] = loops.summary()
        # the memory plane's compact rollup (full tree on /debug/memory)
        out["memory"] = memledger.summary()
        # the device plane's compact rollup (full table on /debug/device)
        out["deviceprof"] = deviceprof.summary()
        if state.tenants is not None:
            out["tenants"] = _tenant_stats(state)
        return web.json_response(out)

    @routes.post("/admin/flush")
    async def admin_flush(_req: web.Request) -> web.Response:
        """Force-drain every WAL-fronted memtable to SSTs now (and
        advance WAL truncation).  No-op tables report nothing; a
        cluster-front server has no local tables to flush."""
        flush = getattr(state.engine, "flush", None)
        if flush is None:
            return web.json_response(
                {"error": "flush is a per-node operation; this server "
                          "fronts a cluster — flush each region's own "
                          "server"}, status=501)
        try:
            return web.json_response(await flush())
        except Error as e:
            return _error_response(e)

    @routes.get("/admin/rollups")
    async def admin_rollups_status(_req: web.Request) -> web.Response:
        """Standing-rollup status: per-spec lag (newest raw seq vs
        newest rolled-up seq), segment coverage, serve counters, and
        per-tier cell volume (docs/rollups.md)."""
        rollups = getattr(state.engine, "rollups", None)
        if rollups is None:
            return web.json_response(
                {"error": "rollups are not enabled on this server "
                          "([rollup] enabled = true)"}, status=501)
        return web.json_response(await rollups.stats())

    @routes.post("/admin/rollups")
    async def admin_rollups(req: web.Request) -> web.Response:
        """Register a standing downsample query: {"metric", "field"?}.
        Optional {"roll": true} runs a synchronous maintenance pass
        (initial backfill / test hook) before answering; registration
        alone backfills on the next background pass."""
        rollups = getattr(state.engine, "rollups", None)
        if rollups is None:
            return web.json_response(
                {"error": "rollups are not enabled on this server "
                          "([rollup] enabled = true)"}, status=501)
        try:
            body = await req.json()
            if not isinstance(body, dict):
                raise ValueError("body must be a JSON object")
            metric = body.get("metric")
            field = str(body.get("field", "value"))
            roll = bool(body.get("roll", False))
            if metric is not None and not isinstance(metric, str):
                raise ValueError("metric must be a string")
        except (TypeError, ValueError) as e:
            return web.json_response({"error": f"bad request: {e}"},
                                     status=400)
        try:
            if metric:
                await rollups.register(metric, field)
            rolled = await rollups.roll_now() if roll else None
        except Error as e:
            return _error_response(e)
        out = await rollups.stats()
        if rolled is not None:
            out["rolled_segments"] = rolled
        return web.json_response(out)

    @routes.get("/admin/tenants")
    async def admin_tenants_status(_req: web.Request) -> web.Response:
        """Per-tenant isolation state: configured limits, quota bucket
        levels, server-side latency quantiles, admission occupancy."""
        if state.tenants is None:
            return web.json_response(
                {"error": "tenants are not enabled on this server "
                          "([tenants] enabled = true)"}, status=501)
        return web.json_response({"enabled": True,
                                  "tenants": _tenant_stats(state)})

    @routes.post("/admin/tenants")
    async def admin_tenants(req: web.Request) -> web.Response:
        """Reload the [tenants] table at runtime: the body is a
        [tenants]-shaped JSON object (default/tenant/auto knobs).
        Limits re-point live (queued waiters keep their place); bucket
        levels reset (a reload is a policy change, not an accounting
        continuation); tenants REMOVED from the config have their
        metric children deregistered so /metrics stops serving them.
        Toggling `enabled` requires a restart — the middleware chain
        is fixed at startup."""
        if state.tenants is None:
            return web.json_response(
                {"error": "tenants are not enabled on this server "
                          "([tenants] enabled = true)"}, status=501)
        try:
            body = await req.json()
            if not isinstance(body, dict):
                raise Error("body must be a JSON object")
            body.setdefault("enabled", True)
            new_cfg = tenants_from_dict(body)
            ensure(new_cfg.enabled,
                   "cannot disable [tenants] at runtime; restart with "
                   "enabled = false")
        except (TypeError, ValueError, Error) as e:
            return web.json_response({"error": f"bad request: {e}"},
                                     status=400)
        removed = state.tenants.configure(new_cfg)
        tstats = state.tenants.stats()
        return web.json_response({"removed": removed, "tenants": tstats})

    @routes.post("/admin/rebalance")
    async def admin_rebalance(req: web.Request) -> web.Response:
        """Hot-shard recommendation hook: the cluster's health monitor
        keeps a split/rebalance proposal from its per-region load
        survey (cluster.py, surfaced on /debug/tasks too); this
        endpoint recomputes it on demand.  ?skew_ratio= overrides the
        flag threshold for this call.  The operator (or an external
        controller) executes the moves — this node cannot know its
        peers' capacities."""
        survey = getattr(state.engine, "survey_load", None)
        if survey is None:
            return web.json_response(
                {"error": "rebalance is a cluster-tier operation; this "
                          "server fronts a single engine"}, status=501)
        skew = None
        raw = req.query.get("skew_ratio")
        if raw is not None:
            try:
                skew = float(raw)
                ensure(skew > 1.0, "skew_ratio must be > 1")
            except (ValueError, Error):
                return web.json_response(
                    {"error": f"bad skew_ratio: {raw!r}"}, status=400)
        out = await (survey(skew_ratio=skew) if skew is not None
                     else survey())
        return web.json_response(out)

    @routes.post("/write")
    async def write(req: web.Request) -> web.Response:
        try:
            body = await req.json()
            samples = [
                Sample(name=s["name"],
                       labels=[Label(k, str(v))
                               for k, v in sorted(s.get("labels", {}).items())],
                       timestamp=int(s["timestamp"]), value=float(s["value"]),
                       field_name=s.get("field", "value"))
                for s in body["samples"]
            ]
        except (KeyError, TypeError, ValueError) as e:
            return web.json_response({"error": f"bad request: {e}"}, status=400)
        try:
            await state.engine.write(samples)
        except Error as e:
            return _error_response(e)
        return web.json_response({"written": len(samples)})

    @routes.post("/write_arrow")
    async def write_arrow(req: web.Request) -> web.Response:
        """Bulk columnar ingest: the body is an Arrow IPC stream (one or
        more record batches with [tags..., timestamp, value] columns);
        metric and tag columns come from query params.  This is the
        Arrow-IPC data plane — no per-row JSON, C++ decode straight into
        the vectorized ingest path."""
        import pyarrow.ipc

        metric = req.query.get("metric")
        if not metric:
            return web.json_response({"error": "metric param required"},
                                     status=400)
        tags = [t for t in req.query.get("tags", "").split(",") if t]
        field = req.query.get("field", "value")
        body = await req.read()
        try:
            reader = pyarrow.ipc.open_stream(body)
            table = reader.read_all()
        except Exception as e:  # arrow raises several types here
            return web.json_response({"error": f"bad arrow stream: {e}"},
                                     status=400)
        written = 0
        try:
            for batch in table.combine_chunks().to_batches():
                await state.engine.write_arrow(metric, tags, batch,
                                               field=field)
                written += batch.num_rows
        except Error as e:
            return _error_response(e)
        return web.json_response({"written": written})

    def _parse_query_body(body: dict):
        """Shared /query + /query_arrow request parsing.  The dict filter
        form loses duplicate keys; the list-of-pairs form (RemoteRegion
        sends it) preserves them.  bucket_ms converts HERE so a
        non-numeric value is a 400, not a 500 mid-handler."""
        metric = body["metric"]
        raw_filters = body.get("filters", {})
        if isinstance(raw_filters, dict):
            filters = sorted(raw_filters.items())
        else:
            filters = sorted((str(k), str(v)) for k, v in raw_filters)
        rng = TimeRange.new(int(body["start"]), int(body["end"]))
        field = body.get("field", "value")
        bucket_ms = body.get("bucket_ms")
        bucket_ms = int(bucket_ms) if bucket_ms else None
        return metric, filters, rng, field, bucket_ms

    def _resolve_fn(fn):
        """Whitelisted rate-family post-functions.  Explicit whitelist:
        getattr dispatch would accept module attributes (fn="np") and
        500 on call.  Returns (impl, error_response)."""
        from horaedb_tpu.metric_engine import functions

        supported = {"rate": functions.rate,
                     "increase": functions.increase,
                     "delta": functions.delta}
        impl = supported.get(fn) if isinstance(fn, str) else None
        if impl is None:
            return None, web.json_response(
                {"error": f"unknown fn {fn!r}; supported: "
                          f"{sorted(supported)}"}, status=400)
        return impl, None

    @routes.post("/query")
    async def query(req: web.Request) -> web.Response:
        try:
            body = await req.json()
            metric, filters, rng, field, bucket_ms = _parse_query_body(body)
            fn = body.get("fn")
        except (KeyError, TypeError, ValueError) as e:
            return web.json_response({"error": f"bad request: {e}"}, status=400)
        # reject an unknown fn BEFORE paying for the scan
        impl = None
        if bucket_ms and fn is not None:
            impl, err = _resolve_fn(fn)
            if err is not None:
                return err
        try:
            if bucket_ms:
                out, meta = await _engine_downsample(metric, filters, rng,
                                                     bucket_ms, field)
                body_out = _downsample_json(out)
                if impl is not None and out["tsids"]:
                    body_out["aggs"][fn] = _grid_json(
                        impl(out["aggs"], bucket_ms))
                return web.json_response(_attach_partial(body_out, meta))
            tbl, meta = await _engine_query(metric, filters, rng, field)
            return web.json_response(_attach_partial({
                "tsids": [str(t) for t in tbl.column("tsid").to_pylist()],
                "timestamps": tbl.column("timestamp").to_pylist(),
                "values": tbl.column("value").to_pylist()}, meta))
        except Error as e:
            return _error_response(e)

    @routes.post("/query_topk")
    async def query_topk(req: web.Request) -> web.Response:
        """Top-k series by one aggregate over the window (BASELINE
        config 4's shape), via the engine's TopK QueryPlan stage.  Body:
        {metric, filters?, start, end, bucket_ms, k, by?, largest?,
        field?} — results come back best-first."""
        try:
            body = await req.json()
            metric, filters, rng, field, bucket_ms = _parse_query_body(body)
            if not bucket_ms:
                raise ValueError("bucket_ms is required")
            k = int(body["k"])
            if k < 1:
                raise ValueError("k must be >= 1")
            by = str(body.get("by", "max"))
            largest = bool(body.get("largest", True))
        except (KeyError, TypeError, ValueError) as e:
            return web.json_response({"error": f"bad request: {e}"},
                                     status=400)
        try:
            out = await state.engine.query_topk(
                metric, filters, rng, bucket_ms, k=k, by=by,
                largest=largest, field=field)
        except Error as e:
            return _error_response(e)
        return web.json_response(_downsample_json(out))

    @routes.post("/query_multi")
    async def query_multi(req: web.Request) -> web.Response:
        """Downsample SEVERAL fields of one metric in one request (one
        resolve, per-field pushdown scans).  Body: {metric, filters?,
        start, end, bucket_ms, fields: [..]}; response maps field ->
        the /query downsample shape."""
        try:
            body = await req.json()
            metric, filters, rng, field, bucket_ms = _parse_query_body(body)
            if not bucket_ms:
                raise ValueError("bucket_ms is required")
            fields = body["fields"]
            if (not isinstance(fields, list) or not fields
                    or not all(isinstance(f, str) for f in fields)):
                raise ValueError("fields must be a non-empty list of "
                                 "strings")
        except (KeyError, TypeError, ValueError) as e:
            return web.json_response({"error": f"bad request: {e}"},
                                     status=400)
        try:
            outs = await state.engine.query_downsample_multi(
                metric, filters, rng, bucket_ms, fields=fields)
        except Error as e:
            return _error_response(e)
        return web.json_response({f: _downsample_json(out)
                                  for f, out in outs.items()})

    @routes.post("/query_arrow")
    async def query_arrow(req: web.Request) -> web.Response:
        """Like POST /query but the response body is an Arrow IPC
        stream — the symmetric read side of the Arrow data plane.  With
        "bucket_ms" the response is the downsample-grid encoding
        (common.ipc.downsample_to_arrow): one row per series, each
        aggregate a FixedSizeList<f64>[num_buckets] column — the
        region-to-region hop's format (JSON grids decimal-print every
        cell; zstd'd Arrow is 2.6x fewer DCN bytes on random grids,
        more on real data)."""
        from horaedb_tpu.common.ipc import (COMPRESSIONS,
                                            downsample_to_arrow,
                                            serialize_stream)

        try:
            body = await req.json()
            metric, filters, rng, field, bucket_ms = _parse_query_body(body)
            fn = body.get("fn")
            # compressed IPC buffers are OPT-IN ("compression": "zstd"):
            # time-series columns compress well across DCN, but not
            # every Arrow implementation ships every codec
            compression = body.get("compression")
            if compression not in COMPRESSIONS:
                raise ValueError(f"unsupported compression {compression!r}")
        except (KeyError, TypeError, ValueError) as e:
            return web.json_response({"error": f"bad request: {e}"}, status=400)
        # reject an unknown fn BEFORE paying for the scan
        impl = None
        if bucket_ms and fn is not None:
            impl, err = _resolve_fn(fn)
            if err is not None:
                return err
        try:
            if bucket_ms:
                out, meta = await _engine_downsample(metric, filters, rng,
                                                     bucket_ms, field)
                if impl is not None and out["tsids"]:
                    out["aggs"][fn] = impl(out["aggs"], bucket_ms)
                tbl = downsample_to_arrow(out)
            else:
                tbl, meta = await _engine_query(metric, filters, rng,
                                                field)
        except Error as e:
            return _error_response(e)
        return web.Response(body=serialize_stream(tbl, compression),
                            headers=_partial_headers(meta),
                            content_type="application/vnd.apache.arrow.stream")

    @routes.get("/label_names")
    async def label_names(req: web.Request) -> web.Response:
        try:
            metric = req.query["metric"]
            rng = TimeRange.new(int(req.query["start"]), int(req.query["end"]))
        except (KeyError, ValueError) as e:
            return web.json_response({"error": f"bad request: {e}"}, status=400)
        return web.json_response(
            {"names": await state.engine.label_names(metric, rng)})

    @routes.get("/metrics_list")
    async def metrics_list(req: web.Request) -> web.Response:
        try:
            rng = TimeRange.new(int(req.query["start"]), int(req.query["end"]))
        except (KeyError, ValueError) as e:
            return web.json_response({"error": f"bad request: {e}"}, status=400)
        return web.json_response(
            {"metrics": await state.engine.list_metrics(rng)})

    @routes.get("/label_values")
    async def label_values(req: web.Request) -> web.Response:
        try:
            metric = req.query["metric"]
            key = req.query["key"]
            rng = TimeRange.new(int(req.query["start"]), int(req.query["end"]))
        except (KeyError, ValueError) as e:
            return web.json_response({"error": f"bad request: {e}"}, status=400)
        try:
            gather = getattr(state.engine, "label_values_gather", None)
            if gather is not None:
                vals, meta = await gather(metric, key, rng)
                return web.json_response(
                    _attach_partial({"values": vals}, meta))
            vals = await state.engine.label_values(metric, key, rng)
        except Error as e:
            return _error_response(e)
        return web.json_response({"values": vals})

    # ---- replication ops plane (cluster/replication.py) -------------------
    # Ungoverned: followers bound every RPC client-side (HttpWalSource
    # carries an explicit timeout + X-Deadline-Ms), and shipping must
    # keep draining even when the admission gate is shedding client
    # load — replication lag during overload makes failover WORSE.

    @routes.get("/repl/wal/segments")
    async def repl_segments(req: web.Request) -> web.Response:
        if state.repl is None:
            return web.json_response(
                {"error": "replication not enabled on this node"},
                status=501)
        follower = req.query.get("follower")
        return web.json_response(state.repl.snapshot(follower_id=follower))

    @routes.get("/repl/wal/read")
    async def repl_read(req: web.Request) -> web.Response:
        if state.repl is None:
            return web.json_response(
                {"error": "replication not enabled on this node"},
                status=501)
        try:
            log = req.query["log"]
            segment = int(req.query["segment"])
            offset = int(req.query["offset"])
            max_bytes = int(req.query["max_bytes"])
        except (KeyError, ValueError) as e:
            return web.json_response({"error": f"bad request: {e}"},
                                     status=400)
        if offset < 0 or max_bytes <= 0:
            # range-check here: out-of-range values trip Wal.read_tail's
            # internal ensure(), which would surface as a 500
            return web.json_response(
                {"error": "bad request: offset must be >= 0 and "
                          "max_bytes > 0"}, status=400)
        out = await state.repl.read_tail(log, segment, offset, max_bytes)
        if out is None:
            # segment truncated (or unknown log): the follower resyncs
            # from a fresh listing instead of treating this as an error
            return web.Response(body=b"", headers={"X-Wal-Gone": "1"})
        blob, sealed = out
        return web.Response(body=blob,
                            headers={"X-Wal-Sealed": "1" if sealed else "0"},
                            content_type="application/octet-stream")

    @routes.post("/repl/wal/ack")
    async def repl_ack(req: web.Request) -> web.Response:
        if state.repl is None:
            return web.json_response(
                {"error": "replication not enabled on this node"},
                status=501)
        try:
            body = await req.json()
            follower = str(body["follower"])
            acks = {str(k): int(v) for k, v in body["acks"].items()}
        except (KeyError, TypeError, ValueError, AttributeError) as e:
            return web.json_response({"error": f"bad request: {e}"},
                                     status=400)
        state.repl.ack(follower, acks)
        return web.json_response({"ok": True})

    @routes.get("/repl/status")
    async def repl_status(req: web.Request) -> web.Response:
        body: dict = {"role": "none"}
        if state.repl is not None:
            body = state.repl.status()
            body["role"] = "primary"
        elif state.follower is not None:
            body["role"] = "follower"
            body["lag_seqs"] = state.follower.lag()
            body["shipped_seqs"] = dict(state.follower.shipped_seqs)
        if state.monitor is not None:
            # [failover]: a node running a standby monitor is a
            # STANDBY until it wins an election — even though it also
            # carries a shipping hub (cascading standbys tail it), the
            # monitor's role is the truth.  The election dict (observed
            # epoch, grace deadline, last outcome) is the same one the
            # monitor's loop backlog serves on /debug/tasks.
            election = state.monitor.election_state()
            if election["role"] == "standby":
                body["role"] = "standby"
            body["election"] = election
        if state.lease is not None:
            body["lease"] = {"region": state.lease.region,
                             "epoch": state.lease.epoch,
                             "lost": state.lease.lost}
        if state.stale_owner is not None:
            body["stale_owner"] = state.stale_owner
        return web.json_response(body)

    # sized for the Arrow-IPC bulk data plane (default 1 MiB would 413
    # any real ingest batch); the tenant middleware is outermost (the
    # identity must be ambient before the trace roots and the
    # admission decision), then tracing so the trace covers the
    # admission wait and the 504 mapping
    app = web.Application(client_max_size=256 * 1024 * 1024,
                          middlewares=[_tenant_middleware(state),
                                       _tracing_middleware(state),
                                       _resilience_middleware(state)])
    app.add_routes(routes)
    return app


def _grid_json(grid) -> list:
    out = []
    for row in grid.tolist():
        out.append([None if isinstance(x, float) and math.isnan(x) else x
                    for x in row])
    return out


def _downsample_json(out: dict) -> dict:
    """THE wire shape of a downsample result, shared by /query,
    /query_topk and /query_multi so the endpoints cannot drift."""
    return {"tsids": [str(t) for t in out["tsids"]],
            "num_buckets": out["num_buckets"],
            "aggs": {k: _grid_json(v) for k, v in out["aggs"].items()}}


def _build_store(config: ServerConfig):
    from horaedb_tpu.objstore import InstrumentedStore

    oc = config.metric_engine.object_store
    if oc.kind == "S3Like":
        from horaedb_tpu.objstore.s3 import S3ObjectStore, S3Options

        store = S3ObjectStore(S3Options(
            endpoint=oc.s3.endpoint, region=oc.s3.region or "us-east-1",
            bucket=oc.s3.bucket, access_key_id=oc.s3.key_id,
            secret_access_key=oc.s3.key_secret, prefix=oc.s3.prefix,
            max_retries=oc.s3.max_retries))
    else:
        store = LocalObjectStore(oc.data_dir)
    # per-op objstore counters/latency histograms surface at /metrics
    return InstrumentedStore(store)


async def run_server(config: ServerConfig,
                     ready: Optional[asyncio.Event] = None) -> None:
    import dataclasses
    import os

    store = _build_store(config)
    wal_config = config.wal
    if wal_config.enabled and not wal_config.dir:
        # the WAL lives beside the Local object-store root (load_config
        # rejects empty-dir WAL on remote stores)
        wal_config = dataclasses.replace(
            wal_config,
            dir=os.path.join(config.metric_engine.object_store.data_dir,
                             "wal"))
    engine = await MetricEngine.open(
        "metrics", store,
        segment_ms=config.metric_engine.segment_duration.millis,
        config=config.metric_engine.time_merge_storage,
        chunked_data=config.metric_engine.chunked_data,
        chunk_window_ms=config.metric_engine.chunk_window.millis,
        wal_config=wal_config, rollup_config=config.rollup,
        meta_config=config.meta, scanagent_config=config.scanagent)
    state = ServerState(engine, config)
    await state.start_replication(store)
    if config.test.enable_write:
        state.start_generators()

    app = build_app(state)
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", config.port)
    await site.start()
    logger.info("listening on 127.0.0.1:%d", config.port)
    if ready is not None:
        ready.set()
    try:
        while True:
            await asyncio.sleep(3600)
    finally:
        await state.stop_generators()
        await state.stop_replication()
        await runner.cleanup()
        await engine.close()
        closer = getattr(store, "close", None)
        if closer is not None:
            await closer()


def main() -> None:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s %(name)s:%(lineno)d %(message)s")
    parser = argparse.ArgumentParser("horaedb-tpu-server")
    parser.add_argument("--config", default=None, help="TOML config path")
    args = parser.parse_args()
    config = load_config(args.config)
    asyncio.run(run_server(config))


if __name__ == "__main__":
    main()
