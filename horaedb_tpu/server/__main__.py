from horaedb_tpu.server.main import main

main()
