"""Server configuration (ref: src/server/src/config.rs:21-175).

Same layered-TOML shape: port, test write-load generator knobs, and the
metric-engine section holding the object-store choice plus the
TimeMergeStorage config.  The reference defines the S3 keys fully
(config.rs:82-160) but panics on selection (main.rs:112); here
kind = "S3Like" is actually supported via objstore.s3.S3ObjectStore
(endpoint/bucket/credentials validated at load time).
"""

from __future__ import annotations

import dataclasses
import functools
import typing
from dataclasses import dataclass, field
from typing import Any, Optional

from horaedb_tpu.common import Error, ReadableDuration, ReadableSize, ensure
from horaedb_tpu.common.tenant import TenantsConfig, tenants_from_dict
from horaedb_tpu.cluster.breaker import BreakerConfig
from horaedb_tpu.cluster.replication import (FailoverConfig,
                                             RebalanceConfig,
                                             ReplicationConfig)
from horaedb_tpu.metric_engine.meta import MetaConfig
from horaedb_tpu.rollup.config import RollupConfig, rollup_from_dict
from horaedb_tpu.scanagent.config import ScanAgentConfig, scanagent_from_dict
from horaedb_tpu.storage.config import StorageConfig, _check_scalar
from horaedb_tpu.storage.config import from_dict as storage_from_dict
from horaedb_tpu.wal.config import WalConfig


@dataclass
class AdmissionConfig:
    """[admission]: server-side query admission control + per-endpoint
    deadlines (docs/robustness.md, query-path failure domains).

    At most `max_concurrent_queries` queries execute at once; up to
    `max_queued` more wait at most `queue_timeout` for a slot.  Beyond
    that the server SHEDS: 429 when the wait queue is full, 503 when
    the queued wait times out, both with a Retry-After header — under
    overload, fast rejection beats slow collapse (TiLT/PAPERS.md:
    bounding per-request latency keeps a time-centric engine usable)."""

    enabled: bool = True
    max_concurrent_queries: int = 64
    max_queued: int = 128
    queue_timeout: ReadableDuration = field(
        default_factory=lambda: ReadableDuration.parse("500ms"))
    # per-endpoint default deadlines; a client may shrink (never grow
    # past max_timeout) via the X-Deadline-Ms header or timeout_ms param
    query_timeout: ReadableDuration = field(
        default_factory=lambda: ReadableDuration.parse("30s"))
    write_timeout: ReadableDuration = field(
        default_factory=lambda: ReadableDuration.parse("30s"))
    max_timeout: ReadableDuration = field(
        default_factory=lambda: ReadableDuration.parse("5m"))
    # floor for the Retry-After hint on 429/503 responses; the served
    # value is load-aware (derived from queue depth / observed service
    # rate, capped at max_retry_after) and falls back to this floor
    # when no service rate has been observed yet
    retry_after: ReadableDuration = field(
        default_factory=lambda: ReadableDuration.parse("1s"))
    max_retry_after: ReadableDuration = field(
        default_factory=lambda: ReadableDuration.parse("60s"))


@dataclass
class TraceConfig:
    """[trace]: request-scoped tracing (docs/observability.md).  Every
    query/write gets an X-Trace-Id; sampled traces record a span tree
    into a bounded in-memory ring served at /debug/traces, and traces
    over `slow_threshold` (or deadline-exceeded ones) hit the
    slow-query log + the slow_queries_total counter."""

    enabled: bool = True
    # completed traces kept in memory (FIFO eviction)
    ring_size: int = 256
    # at/over this duration a completed trace is logged as a slow query
    slow_threshold: ReadableDuration = field(
        default_factory=lambda: ReadableDuration.parse("1s"))
    # fraction of requests that record spans (the X-Trace-Id header is
    # minted regardless; an upstream-traced request is always recorded)
    sample_rate: float = 1.0
    # background-op traces (compaction, flush, WAL commit rounds,
    # rollup passes, scrub, health rounds) get their OWN ring so hot
    # ops never evict query traces, their own default slow threshold
    # (call sites override per-op), and their own sampling rate
    op_ring_size: int = 256
    op_slow_threshold: ReadableDuration = field(
        default_factory=lambda: ReadableDuration.parse("30s"))
    op_sample_rate: float = 1.0


@dataclass
class WatchdogConfig:
    """[watchdog]: the background-loop watchdog (common/loops.py).
    Every loop spawned through the loop registry heartbeats; a non-idle
    loop whose heartbeat age exceeds its stall threshold fires
    `loop_stalled_total{loop=}` + a slow-log entry, and the flag clears
    when beats resume.  `GET /debug/tasks` serves the full registry."""

    enabled: bool = True
    # watchdog sweep period
    interval: ReadableDuration = field(
        default_factory=lambda: ReadableDuration.parse("1s"))
    # default stall threshold = max(min_stall, stall_factor * period)
    # for loops that don't declare their own threshold
    stall_factor: float = 4.0
    min_stall: ReadableDuration = field(
        default_factory=lambda: ReadableDuration.parse("5s"))


@dataclass
class MemoryConfig:
    """[memory]: the process memory plane (common/memledger.py).
    Every byte-holding component registers a ledger account; an RSS
    sampler loop computes unattributed = RSS - Σ accounts and drives
    soft/hard pressure watermarks.  `GET /debug/memory` serves the
    account tree; memory_account_bytes{account=} / memory_rss_bytes /
    memory_unattributed_bytes land on /metrics (and therefore in the
    meta-ingest __meta table)."""

    enabled: bool = True
    # sampler period
    interval: ReadableDuration = field(
        default_factory=lambda: ReadableDuration.parse("5s"))
    # pressure watermarks on RSS; false pins memory_pressure at 0
    pressure: bool = True
    # "0" auto-derives from the box's MemTotal (soft 70%, hard 85%).
    # memory_pressure reads 0/1/2 and
    # memory_pressure_transitions_total{level=} fires once per episode.
    soft_limit: ReadableSize = field(
        default_factory=lambda: ReadableSize(0))
    hard_limit: ReadableSize = field(
        default_factory=lambda: ReadableSize(0))
    # de-escalation margin: pressure clears only once RSS drops below
    # watermark * (1 - hysteresis), so breathing at the line is one
    # episode, not a counter flood
    hysteresis: float = 0.05


@dataclass
class DeviceprofConfig:
    """[deviceprof]: the device plane (common/deviceprof.py).  Every
    jitted seam routes through the process-global DeviceProfiler
    (lint-enforced: no bare jax.jit outside it), which keeps the
    compile ledger, the dispatch/exec split, h2d/d2h transfer totals,
    and the mesh round timeline.  `GET /debug/device` serves the
    compile-cache table + transfer totals + per-device memory;
    device_compiles_total{fn=} / device_dispatch_seconds{fn=} /
    device_transfer_bytes_total{direction=} land on /metrics."""

    enabled: bool = True
    # recompile-storm watchdog: `storm_threshold` compiles of one fn
    # inside a sliding `storm_window` fire
    # device_recompile_storms_total{fn=} ONCE per episode plus a
    # slow-log line naming the churning cache-key dimension
    storm_window: ReadableDuration = field(
        default_factory=lambda: ReadableDuration.parse("60s"))
    storm_threshold: int = 5
    # mesh round timeline entries kept (FIFO) for /debug/device
    rounds: int = 256


@dataclass
class TestConfig:
    """Write-load generator (ref: config.rs:48-57)."""

    enable_write: bool = False
    write_worker_num: int = 1
    write_interval: ReadableDuration = field(
        default_factory=lambda: ReadableDuration.from_millis(500))


@dataclass
class S3Config:
    """S3-compatible backend settings (same keys the reference defines,
    config.rs:82-160 — but actually supported here via
    objstore.s3.S3ObjectStore, where the reference panics)."""

    region: str = ""
    key_id: str = ""
    key_secret: str = ""
    endpoint: str = ""
    bucket: str = ""
    prefix: str = ""
    max_retries: int = 3


@dataclass
class ObjectStoreConfig:
    kind: str = "Local"  # "Local" | "S3Like"
    data_dir: str = "/tmp/horaedb-tpu"
    s3: Optional[S3Config] = None


@dataclass
class MetricEngineConfig:
    segment_duration: ReadableDuration = field(
        default_factory=lambda: ReadableDuration.parse("2h"))
    # RFC opaque-chunk data layout (Append/BytesMerge path)
    chunked_data: bool = False
    chunk_window: ReadableDuration = field(
        default_factory=lambda: ReadableDuration.parse("30m"))
    object_store: ObjectStoreConfig = field(default_factory=ObjectStoreConfig)
    time_merge_storage: StorageConfig = field(default_factory=StorageConfig)


@dataclass
class ServerConfig:
    port: int = 5000
    test: TestConfig = field(default_factory=TestConfig)
    admission: AdmissionConfig = field(default_factory=AdmissionConfig)
    # per-tenant isolation: weighted-fair admission over per-tenant
    # queues + scan-byte / WAL-rate quotas (common/tenant.py); disabled
    # reproduces the global single-FIFO admission exactly
    tenants: TenantsConfig = field(default_factory=TenantsConfig)
    # circuit breaker / RPC policy for a cluster-backed server's
    # scatter-gather plane (applied when the served engine is a Cluster)
    breaker: BreakerConfig = field(default_factory=BreakerConfig)
    # durable ingest: WAL + memtable front end (wal/ingest.py); with an
    # empty dir and a Local object store, `<data_dir>/wal` is derived
    wal: WalConfig = field(default_factory=WalConfig)
    # standing rollup tiers fed by the ingest path (rollup/manager.py)
    rollup: RollupConfig = field(default_factory=RollupConfig)
    # request-scoped tracing: ring size, slow-query threshold, sampling
    trace: TraceConfig = field(default_factory=TraceConfig)
    # background-loop watchdog (common/loops.py, GET /debug/tasks)
    watchdog: WatchdogConfig = field(default_factory=WatchdogConfig)
    # memory plane: ledger sampler + pressure watermarks
    # (common/memledger.py, GET /debug/memory)
    memory: MemoryConfig = field(default_factory=MemoryConfig)
    # device plane: compile ledger + dispatch profiler + transfer
    # accounting (common/deviceprof.py, GET /debug/device)
    deviceprof: DeviceprofConfig = field(default_factory=DeviceprofConfig)
    # replication plane: WAL shipping + lease-fenced ownership
    # (cluster/replication.py); disabled reproduces single-copy
    # behavior bit-for-bit
    replication: ReplicationConfig = field(default_factory=ReplicationConfig)
    # auto-executed rebalance envelope for survey_load recommendations
    rebalance: RebalanceConfig = field(default_factory=RebalanceConfig)
    # standby self-promotion: the follower's StandbyMonitor election
    # policy (cluster/replication.py); disabled keeps failover an
    # operator/placement-controller decision
    failover: FailoverConfig = field(default_factory=FailoverConfig)
    # self-monitoring meta-ingest (metric_engine/meta.py)
    meta: MetaConfig = field(default_factory=MetaConfig)
    # near-data scan agents: shard map + routing policy (scanagent/);
    # mode = "off" is the direct-scan bit-identity control
    scanagent: ScanAgentConfig = field(default_factory=ScanAgentConfig)
    metric_engine: MetricEngineConfig = field(default_factory=MetricEngineConfig)


@functools.lru_cache(maxsize=None)
def _hints(cls: type) -> dict[str, Any]:
    return typing.get_type_hints(cls)


def _dc_from_dict(cls: type, data: dict[str, Any]) -> Any:
    names = {f.name: f for f in dataclasses.fields(cls)}
    unknown = set(data) - set(names)
    if unknown:
        raise Error(f"unknown config keys for {cls.__name__}: {sorted(unknown)}")
    kwargs: dict[str, Any] = {}
    for key, value in data.items():
        where = f"{cls.__name__}.{key}"
        # dispatch durations by DECLARED type, not a name whitelist —
        # new ReadableDuration fields need no registration here
        if _hints(cls).get(key) is ReadableDuration:
            if not isinstance(value, ReadableDuration):
                ensure(isinstance(value, str),
                       f'{where} expects a duration string like "2h"')
                value = ReadableDuration.parse(value)
            kwargs[key] = value
        elif _hints(cls).get(key) is ReadableSize:
            # sizes dispatch by declared type too: "512MiB" strings or
            # bare byte integers
            if not isinstance(value, ReadableSize):
                ensure(isinstance(value, (str, int))
                       and not isinstance(value, bool),
                       f'{where} expects a size string like "512MiB" '
                       'or a byte count')
                value = (ReadableSize.parse(value)
                         if isinstance(value, str)
                         else ReadableSize(value))
            kwargs[key] = value
        elif key == "test":
            ensure(isinstance(value, dict), f"{where} expects a config table")
            kwargs[key] = _dc_from_dict(TestConfig, value)
        elif key == "admission":
            ensure(isinstance(value, dict), f"{where} expects a config table")
            kwargs[key] = _dc_from_dict(AdmissionConfig, value)
        elif key == "tenants" and cls is ServerConfig:
            ensure(isinstance(value, dict), f"{where} expects a config table")
            kwargs[key] = tenants_from_dict(value)
        elif key == "breaker":
            ensure(isinstance(value, dict), f"{where} expects a config table")
            kwargs[key] = _dc_from_dict(BreakerConfig, value)
        elif key == "wal":
            ensure(isinstance(value, dict), f"{where} expects a config table")
            kwargs[key] = _dc_from_dict(WalConfig, value)
        elif key == "rollup" and cls is ServerConfig:
            # ServerConfig.rollup is the [rollup] table; MetaConfig's
            # same-named field is a plain bool (the scalar path below)
            ensure(isinstance(value, dict), f"{where} expects a config table")
            kwargs[key] = rollup_from_dict(value)
        elif key == "trace":
            ensure(isinstance(value, dict), f"{where} expects a config table")
            kwargs[key] = _dc_from_dict(TraceConfig, value)
        elif key == "watchdog":
            ensure(isinstance(value, dict), f"{where} expects a config table")
            kwargs[key] = _dc_from_dict(WatchdogConfig, value)
        elif key == "memory" and cls is ServerConfig:
            ensure(isinstance(value, dict), f"{where} expects a config table")
            kwargs[key] = _dc_from_dict(MemoryConfig, value)
        elif key == "deviceprof":
            ensure(isinstance(value, dict), f"{where} expects a config table")
            kwargs[key] = _dc_from_dict(DeviceprofConfig, value)
        elif key == "replication":
            ensure(isinstance(value, dict), f"{where} expects a config table")
            kwargs[key] = _dc_from_dict(ReplicationConfig, value)
        elif key == "rebalance":
            ensure(isinstance(value, dict), f"{where} expects a config table")
            kwargs[key] = _dc_from_dict(RebalanceConfig, value)
        elif key == "failover":
            ensure(isinstance(value, dict), f"{where} expects a config table")
            kwargs[key] = _dc_from_dict(FailoverConfig, value)
        elif key == "meta":
            ensure(isinstance(value, dict), f"{where} expects a config table")
            kwargs[key] = _dc_from_dict(MetaConfig, value)
        elif key == "scanagent":
            ensure(isinstance(value, dict), f"{where} expects a config table")
            kwargs[key] = scanagent_from_dict(value)
        elif key == "metric_engine":
            ensure(isinstance(value, dict), f"{where} expects a config table")
            kwargs[key] = _dc_from_dict(MetricEngineConfig, value)
        elif key == "object_store":
            ensure(isinstance(value, dict), f"{where} expects a config table")
            kwargs[key] = _dc_from_dict(ObjectStoreConfig, value)
        elif key == "s3":
            ensure(isinstance(value, dict), f"{where} expects a config table")
            kwargs[key] = _dc_from_dict(S3Config, value)
        elif key == "time_merge_storage":
            ensure(isinstance(value, dict), f"{where} expects a config table")
            kwargs[key] = storage_from_dict(StorageConfig, value)
        else:
            # scalar fields: validate against the declared type at load time
            kwargs[key] = _check_scalar(cls, names[key], value, where)
    return cls(**kwargs)


def load_config(path: Optional[str] = None) -> ServerConfig:
    if path is None:
        return ServerConfig()
    try:
        import tomllib  # stdlib on py3.11+
    except ModuleNotFoundError:
        # py3.10: tomllib IS tomli, and pip always vendors tomli — use
        # it rather than making config files unloadable (no installs
        # available in the deployment image)
        from pip._vendor import tomli as tomllib

    with open(path, "rb") as f:
        data = tomllib.load(f)
    cfg = _dc_from_dict(ServerConfig, data)
    kind = cfg.metric_engine.object_store.kind
    if kind not in ("Local", "S3Like"):
        raise Error(f"object store {kind!r} not supported "
                    "(expected Local or S3Like)")
    if kind == "S3Like":
        s3 = cfg.metric_engine.object_store.s3
        ensure(s3 is not None and s3.endpoint and s3.bucket
               and s3.key_id and s3.key_secret,
               "S3Like object store requires [metric_engine.object_store.s3] "
               "with endpoint, bucket, key_id, and key_secret")
    if cfg.wal.enabled and not cfg.wal.dir:
        # the WAL lives on local disk beside the object-store root; a
        # remote store has no local root to derive it from
        ensure(kind == "Local",
               "[wal] with an empty dir requires a Local object store "
               "(it derives <data_dir>/wal); set wal.dir explicitly")
    ensure(0.0 <= cfg.trace.sample_rate <= 1.0,
           "[trace] sample_rate must be in [0, 1]")
    ensure(cfg.trace.ring_size >= 1, "[trace] ring_size must be >= 1")
    ensure(0.0 <= cfg.trace.op_sample_rate <= 1.0,
           "[trace] op_sample_rate must be in [0, 1]")
    ensure(cfg.trace.op_ring_size >= 1,
           "[trace] op_ring_size must be >= 1")
    ensure(cfg.watchdog.stall_factor >= 1.0,
           "[watchdog] stall_factor must be >= 1")
    ensure(cfg.watchdog.interval.seconds > 0,
           "[watchdog] interval must be positive")
    ensure(cfg.memory.interval.seconds > 0,
           "[memory] interval must be positive")
    ensure(cfg.deviceprof.storm_threshold >= 2,
           "[deviceprof] storm_threshold must be >= 2 (1 would flag "
           "every cold compile as a storm)")
    ensure(cfg.deviceprof.storm_window.seconds > 0,
           "[deviceprof] storm_window must be positive")
    ensure(cfg.deviceprof.rounds >= 1,
           "[deviceprof] rounds must be >= 1")
    ensure(0.0 <= cfg.memory.hysteresis <= 0.5,
           "[memory] hysteresis must be in [0, 0.5]")
    if cfg.memory.soft_limit.bytes and cfg.memory.hard_limit.bytes:
        ensure(cfg.memory.soft_limit.bytes <= cfg.memory.hard_limit.bytes,
               "[memory] soft_limit must not exceed hard_limit")
    if cfg.replication.enabled:
        ensure(cfg.replication.lease_ttl.seconds > 0,
               "[replication] lease_ttl must be positive")
        ensure(2 * cfg.replication.renew_interval.seconds
               < cfg.replication.lease_ttl.seconds,
               "[replication] renew_interval must be under half of "
               "lease_ttl (a lease must survive one missed renewal "
               "with margin, or the fence can expire mid-flush)")
        ensure(cfg.replication.poll_interval.seconds > 0,
               "[replication] poll_interval must be positive")
        ensure(cfg.replication.max_batch_bytes >= 1,
               "[replication] max_batch_bytes must be >= 1")
        if cfg.replication.primary_url:
            ensure(bool(cfg.replication.mirror_dir),
                   "[replication] a follower (primary_url set) needs "
                   "mirror_dir for its local WAL mirror")
    if cfg.failover.enabled:
        ensure(cfg.replication.enabled,
               "[failover] requires [replication] enabled (a standby "
               "monitor watches the replication lease records)")
        ensure(bool(cfg.replication.primary_url)
               and bool(cfg.replication.mirror_dir),
               "[failover] runs on a follower: set [replication] "
               "primary_url and mirror_dir")
        ensure(cfg.failover.grace.seconds
               >= cfg.replication.renew_interval.seconds,
               "[failover] grace must be at least one [replication] "
               "renew_interval (a shorter grace window elects over a "
               "live primary's single renewal hiccup — flapping)")
        ensure(cfg.failover.check_interval.seconds > 0,
               "[failover] check_interval must be positive")
        ensure(cfg.failover.jitter >= 0.0,
               "[failover] jitter must be >= 0")
        ensure(cfg.failover.fitness_wait.seconds >= 0.0,
               "[failover] fitness_wait must be >= 0")
    if cfg.rebalance.enabled:
        ensure(cfg.rebalance.max_concurrent_moves >= 1,
               "[rebalance] max_concurrent_moves must be >= 1")
        ensure(cfg.rebalance.skew_ratio > 1.0,
               "[rebalance] skew_ratio must be > 1")
        ensure(cfg.rebalance.interval.seconds > 0,
               "[rebalance] interval must be positive")
    if cfg.meta.enabled:
        ensure(cfg.meta.interval.seconds > 0,
               "[meta] interval must be positive")
        ensure(bool(cfg.meta.metric),
               "[meta] metric must be non-empty")
        ensure(cfg.meta.max_series >= 1,
               "[meta] max_series must be >= 1")
    if cfg.rollup.enabled:
        ensure(not cfg.metric_engine.chunked_data,
               "[rollup] requires the row data layout "
               "(chunked_data = false)")
        seg = cfg.metric_engine.segment_duration.millis
        for t in cfg.rollup.tier_millis():
            ensure(seg % t == 0,
                   f"[rollup] tier {t}ms must evenly divide "
                   f"segment_duration ({seg}ms)")
    return cfg
