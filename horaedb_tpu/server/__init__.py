"""HTTP server + config (ref: src/server)."""

from horaedb_tpu.server.config import ServerConfig, load_config

__all__ = ["ServerConfig", "load_config"]
