"""Durable ingest subsystem: WAL + memtables in front of
TimeMergeStorage (see wal/ingest.py for the architecture note)."""

from horaedb_tpu.wal.config import WalConfig
from horaedb_tpu.wal.ingest import IngestStorage
from horaedb_tpu.wal.log import Wal, WalError, WalRecord
from horaedb_tpu.wal.memtable import MemEntry, Memtable

__all__ = ["IngestStorage", "MemEntry", "Memtable", "Wal", "WalConfig",
           "WalError", "WalRecord"]
