"""Per-time-segment mutable write buffer.

A memtable accumulates acked-but-unflushed writes of ONE time segment
(keyed exactly like SSTs: range-start truncation, sst.segment_of).  It
serves reads immediately — `stamped_batches` hands the scan path
full-schema batches with each entry's original write seq filled into
`__seq__`, so the hybrid merge dedups memtable rows against SST rows
under the one last-value discipline — and drains to a single SST via
`drain()` when the flusher decides it crossed a threshold.

Seqs are PRESERVED end to end (write -> WAL -> memtable -> flushed
SST): restamping at flush time would let a flush race a concurrent
write and elevate old rows above a newer, already-allocated seq.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import pyarrow as pa

from horaedb_tpu.storage.types import StorageSchema, TimeRange
from horaedb_tpu.utils import registry

_MEM_ROWS = registry.gauge(
    "memtable_rows", "acked rows buffered in memtables, not yet in SSTs")
_MEM_BYTES = registry.gauge(
    "memtable_bytes", "arrow bytes buffered in memtables")


@dataclass
class MemEntry:
    seq: int
    batch: pa.RecordBatch  # user schema
    time_range: TimeRange
    # stamped (full-schema, seq-filled) twin, built lazily ONCE — the
    # hybrid scan snapshots every entry per query and the bytes never
    # change
    _stamped: Optional[pa.RecordBatch] = None

    def stamped(self, schema: StorageSchema) -> pa.RecordBatch:
        if self._stamped is None:
            self._stamped = schema.fill_builtin_columns(self.batch,
                                                        self.seq)
        return self._stamped


class Memtable:
    def __init__(self, segment_start: int, created_at: float):
        self.segment_start = segment_start
        self.created_at = created_at  # injected-clock time of first entry
        self.entries: list[MemEntry] = []
        self.rows = 0
        self.bytes = 0

    def add(self, entry: MemEntry) -> None:
        self.entries.append(entry)
        self.rows += entry.batch.num_rows
        self.bytes += entry.batch.nbytes
        _MEM_ROWS.inc(entry.batch.num_rows)
        _MEM_BYTES.inc(entry.batch.nbytes)

    def account_drop(self) -> None:
        """Gauge bookkeeping when this memtable leaves the live map
        (flushed or abandoned)."""
        _MEM_ROWS.inc(-self.rows)
        _MEM_BYTES.inc(-self.bytes)

    @property
    def time_range(self) -> Optional[TimeRange]:
        rng = None
        for e in self.entries:
            rng = e.time_range if rng is None else rng.merged(e.time_range)
        return rng

    @property
    def seqs(self) -> list[int]:
        return [e.seq for e in self.entries]

    def stamped_batches(self, schema: StorageSchema,
                        scan_range: Optional[TimeRange] = None
                        ) -> list[pa.RecordBatch]:
        """Full-schema batches with per-entry seqs stamped, entry-level
        filtered by range overlap (the same granularity the manifest
        filters SSTs at — row-exact time filtering stays the
        predicate's job, as on the SST path)."""
        out = []
        for e in self.entries:
            if scan_range is not None and not e.time_range.overlaps(
                    scan_range):
                continue
            if e.batch.num_rows:
                out.append(e.stamped(schema))
        return out

    def drain(self, schema: StorageSchema):
        """(stamped concatenated table, union range, seqs) for the
        flusher — per-row seqs preserved; the SST write sorts by
        (PK, __seq__) so equal-PK runs stay in last-value order."""
        stamped = [e.stamped(schema)
                   for e in self.entries if e.batch.num_rows]
        if not stamped:
            return None, None, self.seqs
        return (pa.Table.from_batches(stamped), self.time_range, self.seqs)
