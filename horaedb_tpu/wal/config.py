"""[wal] configuration: the durable-ingest front end (wal/ingest.py).

No reference analogue — the reference acks a write only after its SST
and manifest delta land in the object store.  With the WAL enabled the
server acks after a group-commit fsync to a local append-only log and
batches rows in memtables, so small writes stop paying a full
object-store round trip each (docs/robustness.md, write durability
failure domains).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from horaedb_tpu.common import ReadableDuration


@dataclass
class WalConfig:
    """Knobs for the WAL + memtable ingest subsystem.

    Group commit: concurrent writers queue framed records; one loop
    writes the queue to the active segment and issues ONE fsync for the
    whole group, then acks every waiter.  `max_group_bytes` flushes a
    group early; `max_group_wait` is the coalescing window a commit
    waits for more writers to pile on (0 = commit immediately).

    Flush: a memtable drains to one SST through the existing write path
    when it crosses `flush_rows` / `flush_bytes` / `flush_age`; only
    after the SST + manifest commit does the WAL truncation point
    advance (crash between the two replays the rows — the `__seq__`
    dedup discipline makes that exactly-once).
    """

    enabled: bool = False
    # WAL directory; empty derives `<object-store data_dir>/wal` for
    # Local stores (a per-table subdirectory is appended by the engine)
    dir: str = ""
    # rotate the active segment file past this many bytes; sealed
    # segments whose records are all flushed are deleted (truncation)
    segment_bytes: int = 64 << 20
    # group-commit triggers.  max_group_wait defaults to 0: writers
    # that arrive during the previous group's fsync already coalesce,
    # and the benchmark (bench config 8) shows an extra coalescing
    # sleep only raises p99 ack latency
    max_group_bytes: int = 1 << 20
    max_group_wait: ReadableDuration = field(
        default_factory=lambda: ReadableDuration.from_millis(0))
    # memtable flush thresholds
    flush_rows: int = 65536
    flush_bytes: int = 8 << 20
    flush_age: ReadableDuration = field(
        default_factory=lambda: ReadableDuration.from_secs(30))
    # background flusher poll period
    flush_interval: ReadableDuration = field(
        default_factory=lambda: ReadableDuration.from_secs(1))
