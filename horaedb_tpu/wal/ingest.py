"""IngestStorage: the durable-ingest front end over TimeMergeStorage.

Write path: validate -> allocate a seq (the SST id space, monotonic
across restarts) -> WAL group-commit append -> ACK after the group
fsync -> buffer the rows in the segment's memtable.  The object store
is not touched per write; a background flusher drains memtables to one
SST each through `CloudObjectStorage.write_stamped` (per-row seqs
preserved) once a memtable crosses flush_rows / flush_bytes /
flush_age, and only after the SST + manifest commit does the WAL
truncation point advance.

Crash safety (docs/robustness.md, write-durability failure domains):
- acked rows are in a fsynced WAL record; replay on open rebuilds the
  memtables, so they survive kill -9;
- a crash between flush commit and truncation replays rows an SST
  already holds — the preserved `__seq__` makes the duplicate collapse
  in the merge (exactly-once after scan);
- a crash mid-group loses only unacked writes (the group's waiters saw
  the failure).

Read path: hybrid scan.  Segments with no memtable overlay take the
unchanged plan/pushdown path; overlay segments are scanned
predicate-free with builtin columns kept and host-merged with the
memtable rows (read.merge_memtable_overlay) so queries see
acked-but-unflushed rows under the one last-value discipline.
Aggregate pushdown plans flush overlapping memtables first — the
device grids then read pure SST state.
"""

from __future__ import annotations

import asyncio
import time
from typing import AsyncIterator, Optional

import pyarrow as pa

import logging

from horaedb_tpu.common.error import ensure
from horaedb_tpu.common.loops import loops
from horaedb_tpu.common.memledger import ledger as memledger
from horaedb_tpu.common.tenant import current_tenant
from horaedb_tpu.storage.config import UpdateMode
from horaedb_tpu.storage.read import (
    ScanPlan,
    ScanRequest,
    merge_memtable_overlay,
    plan_columns,
)
from horaedb_tpu.storage.sst import SstFile
from horaedb_tpu.storage.storage import (
    TimeMergeStorage,
    WriteRequest,
    WriteResult,
)
from horaedb_tpu.utils import (WIDE_BUCKETS, op_trace, registry, span,
                               trace_add)
from horaedb_tpu.wal.config import WalConfig
from horaedb_tpu.wal.log import Wal
from horaedb_tpu.wal.memtable import MemEntry, Memtable

logger = logging.getLogger(__name__)

_FLUSHES = registry.counter(
    "memtable_flushes_total", "memtable -> SST flushes")
_FLUSH_ROWS = registry.counter(
    "memtable_flush_rows_total", "rows drained from memtables into SSTs")
_FLUSH_FAILURES = registry.counter(
    "memtable_flush_failures_total",
    "flush attempts that failed (rows returned to the memtable)")
_REPLAYED_ROWS = registry.counter(
    "wal_replayed_rows_total", "rows rebuilt into memtables by replay")
_ACK_LATENCY = registry.histogram(
    "ingest_ack_seconds", "write() latency to the WAL-fsync ack point")


class IngestStorage(TimeMergeStorage):
    """WAL + memtable wrapper around a CloudObjectStorage.  Everything
    not ingest-related (manifest, scrub, compaction scheduling, reader)
    delegates to the wrapped storage."""

    def __init__(self, inner, wal: Wal, config: WalConfig,
                 clock=time.monotonic, on_op=None):
        self.inner = inner
        self.wal = wal
        self.config = config
        self._clock = clock
        self._on_op = on_op
        self._memtables: dict[int, Memtable] = {}
        # memtables whose flush is IN FLIGHT: they left _memtables (new
        # writes go to a fresh one) but must stay visible to scans until
        # the SST + manifest commit lands — popping first would open a
        # window where acked rows are in neither source
        self._flushing: dict[int, list[Memtable]] = {}
        self._flush_lock = asyncio.Lock()
        self._flusher_task: Optional[asyncio.Task] = None
        self._flush_wake: Optional[asyncio.Event] = None
        self._stopping = False
        self._last_flush_at: Optional[float] = None
        # watchdog test hook: a positive value wedges the flush loop's
        # next iteration (sleeps without heartbeating) so stall
        # detection is testable against a REAL loop (tests/test_loops)
        self.test_stall_s = 0.0
        # newest seq acked by this ingest front end (rollup lag signal)
        self.last_seq = 0
        # flush-commit hook: called with the segment start after an SST
        # + manifest commit lands (the rollup manager's delta feed)
        self.on_flush = None
        # ownership fence (cluster/replication.py): when set, every
        # flush revalidates the region lease BEFORE the SST + manifest
        # commit — a primary whose lease was stolen raises
        # StaleEpochError here and can never commit past its epoch.
        # None = unreplicated region, no fencing (current behavior).
        self.fence = None
        # ledger accounts (memtable bytes + WAL backlog), set by open()
        self._mem_accounts: list = []

    def __getattr__(self, name):
        inner = self.__dict__.get("inner")
        if inner is None:
            raise AttributeError(name)
        return getattr(inner, name)

    # ---- lifecycle --------------------------------------------------------

    @classmethod
    async def open(cls, inner, wal_dir: str, config: WalConfig,
                   clock=time.monotonic, on_op=None) -> "IngestStorage":
        ensure(inner.schema().update_mode is UpdateMode.OVERWRITE,
               "the WAL ingest path requires Overwrite mode: replay "
               "dedups via __seq__, which Append tables do not have")
        wal = Wal(wal_dir, config, on_op=on_op)
        self = cls(inner, wal, config, clock=clock, on_op=on_op)
        records = await asyncio.to_thread(wal.replay)
        user_schema = inner.schema().user_schema
        replayed = 0
        dropped = []
        for rec in records:
            if not rec.batch.schema.equals(user_schema):
                logger.warning(
                    "wal %s: dropping replayed record seq=%s with stale "
                    "schema", wal_dir, rec.seq)
                dropped.append(rec.seq)
                continue
            self._insert(rec.seq, rec.batch, rec.time_range)
            replayed += rec.batch.num_rows
        if dropped:
            # unrecoverable under this schema: mark them flushed so
            # their segments can still truncate instead of pinning the
            # backlog (and re-dropping) on every restart
            wal.mark_flushed(dropped)
        _REPLAYED_ROWS.inc(replayed)
        if replayed:
            logger.info("wal %s: replayed %d rows into %d memtables",
                        wal_dir, replayed, len(self._memtables))
        wal.start()
        self._flush_wake = asyncio.Event()
        # stall threshold sized to a worst-case flush (wide-bucket op:
        # a big memtable's SST write runs minutes), not the poll period
        self._flusher_task = loops.spawn(
            self._flush_loop, name=f"wal-flusher:{wal_dir}",
            kind="wal-flusher", owner="wal",
            period_s=config.flush_interval.seconds,
            stall_threshold_s=300.0,
            backlog=self._flusher_backlog)
        # memory plane (common/memledger.py): acked-but-unflushed rows
        # live twice — arrow batches in memtables AND framed bytes in
        # un-truncated WAL segments.  Both register; the memtable
        # budget is the flush threshold (utilization > 1 = the flusher
        # is behind), the WAL backlog is unbudgeted by design (it
        # truncates after flush).  close() deregisters.
        self._mem_accounts = [
            memledger.register(
                f"memtable:{wal_dir}", lambda s: s.memtable_bytes_now(),
                anchor=self, kind="memtable",
                budget=config.flush_bytes, owner=wal_dir),
            memledger.register(
                f"wal_backlog:{wal_dir}",
                lambda s: s.wal.backlog_bytes, anchor=self,
                kind="wal_backlog", owner=wal_dir),
        ]
        return self

    def memtable_bytes_now(self) -> int:
        """Arrow bytes across live AND flush-in-flight memtables (the
        ledger's pull gauge; flush-in-flight rows are still resident
        until their SST commits)."""
        total = sum(mt.bytes for mt in self._memtables.values())
        for mts in self._flushing.values():
            total += sum(mt.bytes for mt in mts)
        return total

    def _flusher_backlog(self) -> dict:
        """/debug/tasks backlog hint: what the flusher is behind on."""
        s = self.ingest_stats()
        return {"memtable_rows": s["memtable_rows"],
                "memtable_bytes": s["memtable_bytes"],
                "wal_backlog_bytes": s["wal_backlog_bytes"]}

    async def close(self, flush: bool = True) -> None:
        self._stopping = True
        if self._flusher_task is not None:
            self._flush_wake.set()
            try:
                await self._flusher_task
            except asyncio.CancelledError:
                pass
            self._flusher_task = None
        if flush:
            try:
                await self.flush_all()
            except Exception as exc:  # noqa: BLE001 — rows stay in the WAL
                logger.warning("final flush failed (rows remain in the "
                               "WAL for replay): %s", exc)
        await self.wal.close()
        for mt in self._memtables.values():
            mt.account_drop()
        self._memtables = {}
        for acct in self._mem_accounts:
            memledger.deregister(acct)
        self._mem_accounts = []
        await self.inner.close()

    async def abort(self) -> None:
        """Torture-harness teardown: stop loops WITHOUT flushing (the
        simulated process death already happened)."""
        await self.close(flush=False)

    # ---- write ------------------------------------------------------------

    def _insert(self, seq: int, batch: pa.RecordBatch, time_range) -> int:
        seg = int(time_range.start.truncate_by(
            self.inner.segment_duration_ms))
        mt = self._memtables.get(seg)
        if mt is None:
            mt = self._memtables[seg] = Memtable(seg, self._clock())
        mt.add(MemEntry(seq=seq, batch=batch, time_range=time_range))
        return seg

    async def write(self, req: WriteRequest) -> WriteResult:
        self.inner.validate_write(req)
        # per-tenant ingest-rate gate, AHEAD of the group commit: a
        # flooding tenant is rejected (QuotaExceeded -> 429) before its
        # batch costs a WAL frame, an fsync share, or a seq — the
        # write path's quota lives at the layer that owns the rate
        tenant = current_tenant()
        if tenant is not None:
            tenant.admit_wal(req.batch.nbytes)
        t0 = time.perf_counter()
        seq = SstFile.allocate_id()
        # the span covers frame + enqueue + the group-commit fsync wait
        # (the ack point) — the write path's per-query profile
        with span("wal_append_fsync", rows=req.batch.num_rows):
            size = await self.wal.append(seq, req.time_range, req.batch)
        trace_add("wal_append_bytes", size)
        # the fsync ack point: the rows are durable from here on
        with span("memtable_insert"):
            seg = self._insert(seq, req.batch, req.time_range)
        self.last_seq = max(self.last_seq, seq)
        self._maybe_wake_flusher(self._memtables.get(seg))
        _ACK_LATENCY.observe(time.perf_counter() - t0)
        return WriteResult(id=seq, seq=seq, size=size)

    def _maybe_wake_flusher(self, mt: Optional[Memtable]) -> None:
        """O(1) on the ack hot path: only the memtable the write just
        landed in can have newly crossed a threshold."""
        if self._flush_wake is None or mt is None:
            return
        cfg = self.config
        if mt.rows >= cfg.flush_rows or mt.bytes >= cfg.flush_bytes:
            self._flush_wake.set()

    # ---- flush ------------------------------------------------------------

    async def _flush_loop(self, hb) -> None:
        interval = self.config.flush_interval.seconds
        while not self._stopping:
            try:
                await asyncio.wait_for(self._flush_wake.wait(), interval)
            except asyncio.TimeoutError:
                pass
            if self.test_stall_s:
                # injected stall (watchdog tests): wedge WITHOUT
                # beating, exactly like a hung store call would
                await asyncio.sleep(self.test_stall_s)
            hb.beat()
            self._flush_wake.clear()
            if self._stopping:
                return
            try:
                await self._flush_due()
                hb.ok()
            except asyncio.CancelledError:
                raise
            except Exception as exc:  # noqa: BLE001 — retries next tick
                hb.error(exc)
                logger.exception("memtable flush pass failed")

    def _due(self, mt: Memtable) -> bool:
        cfg = self.config
        return (mt.rows >= cfg.flush_rows or mt.bytes >= cfg.flush_bytes
                or (self._clock() - mt.created_at)
                >= cfg.flush_age.seconds)

    async def _flush_due(self) -> int:
        flushed = 0
        for seg in sorted(self._memtables):
            mt = self._memtables.get(seg)
            if mt is not None and mt.entries and self._due(mt):
                flushed += await self._flush_segment(seg)
        return flushed

    async def flush_all(self) -> int:
        """Drain every memtable now (POST /admin/flush, close, and the
        aggregate-pushdown pre-flush).  Returns rows flushed."""
        return await self.flush_overlapping(None)

    async def flush_overlapping(self, time_range) -> int:
        flushed = 0
        for seg in sorted(self._memtables):
            mt = self._memtables.get(seg)
            if mt is None or not mt.entries:
                continue
            rng = mt.time_range
            if time_range is not None and rng is not None \
                    and not rng.overlaps(time_range):
                continue
            flushed += await self._flush_segment(seg)
        if self._flushing_overlaps(time_range):
            # barrier: a background flush already in flight popped its
            # memtable before we looked — its SST + manifest commit
            # must land before callers replan from the manifest, or an
            # aggregate would silently omit acked rows.  _flush_segment
            # holds _flush_lock for its whole duration, so acquiring it
            # once waits the in-flight flush out.  Only OVERLAPPING
            # in-flight flushes matter: waiting on a disjoint segment's
            # flush would couple tenants through the flush lock (a
            # dashboard aggregate stalling behind another tenant's
            # bulk-ingest flush; docs/robustness.md, tenant isolation).
            async with self._flush_lock:
                pass
        return flushed

    def _flushing_overlaps(self, time_range) -> bool:
        """Whether any in-flight flush holds rows overlapping
        `time_range` (None = any).  A drained memtable keeps its
        entries until the SST commit lands (scan visibility), so its
        time_range stays answerable; None ranges are treated as
        overlapping — correctness over precision."""
        for mts in self._flushing.values():
            for mt in mts:
                rng = mt.time_range
                if (time_range is None or rng is None
                        or rng.overlaps(time_range)):
                    return True
        return False

    async def _flush_segment(self, seg: int) -> int:
        """Drain one memtable to one SST.  Ordering is the crash-safety
        invariant: (1) SST + manifest commit, (2) mark seqs flushed,
        (3) truncate sealed WAL segments.  A crash after (1) replays
        rows the SST already holds — seq-preserving dedup collapses
        them."""
        async with self._flush_lock:
            mt = self._memtables.pop(seg, None)
            if mt is None or not mt.entries:
                if mt is not None:
                    mt.account_drop()
                return 0
            # each flush is a background operation with its own op
            # trace — unless a query's aggregate pre-flush triggered
            # it, in which case it records as that query's span
            # (utils.tracing.op_trace's ambient check)
            with op_trace("flush", slow_s=60.0, segment=seg,
                          rows=mt.rows):
                return await self._flush_taken(seg, mt)

    async def _flush_taken(self, seg: int, mt: Memtable) -> int:
        # the memtable stays scan-visible via _flushing while the
        # SST write is in flight; a concurrent scan's overlay
        # snapshot therefore always holds the rows, and once the
        # manifest commit lands the seq tie dedups the double
        self._flushing.setdefault(seg, []).append(mt)
        try:
            table, rng, seqs = mt.drain(self.inner.schema())
            if table is not None:
                fence = self.fence
                if fence is not None:
                    # cheap pre-flight: fail before paying the SST
                    # upload when the lease is ALREADY gone.  The real
                    # fencing point is pre_commit below — the upload
                    # can run seconds-to-minutes (a whole lease TTL),
                    # so the lease is revalidated again immediately
                    # before the manifest publish; a stale-epoch
                    # holder fails either way with the rows intact
                    # (re-inserted below) for the new primary's
                    # replay to cover
                    await fence.check()
                if self._on_op is not None:
                    self._on_op("flush")
                # flushes run seconds-to-minutes on big memtables:
                # the wide buckets keep them out of the +Inf bin
                with span("memtable_flush", buckets=WIDE_BUCKETS,
                          segment=seg, rows=mt.rows):
                    if fence is not None:
                        await self.inner.write_stamped(
                            table, rng, pre_commit=fence.check)
                    else:
                        await self.inner.write_stamped(table, rng)
        except BaseException:
            # the rows are acked: put them back so reads keep
            # serving them; the WAL still covers them for replay
            _FLUSH_FAILURES.inc()
            self._flushing[seg].remove(mt)
            mt.account_drop()
            cur = self._memtables.get(seg)
            if cur is None:
                cur = self._memtables[seg] = Memtable(
                    seg, mt.created_at)
            for e in mt.entries:
                cur.add(e)
            raise
        finally:
            if mt in self._flushing.get(seg, ()):
                self._flushing[seg].remove(mt)
            if not self._flushing.get(seg):
                self._flushing.pop(seg, None)
        mt.account_drop()
        self.wal.mark_flushed(seqs)
        await self.wal.truncate()
        self._last_flush_at = self._clock()
        _FLUSHES.inc()
        _FLUSH_ROWS.inc(mt.rows)
        if self.on_flush is not None:
            self.on_flush(seg)
        return mt.rows

    # ---- read -------------------------------------------------------------

    def _snapshot_overlay(self, scan_range) -> dict[int, list]:
        """Segment -> stamped memtable batches overlapping the scan.
        Taken BEFORE the SST plan is built: a flush racing the scan can
        only move rows into SSTs the later plan SEES, so rows appear in
        at least one source (the seq tie collapses doubles)."""
        out: dict[int, list] = {}
        schema = self.inner.schema()
        flushing = [(seg, mt) for seg, mts in self._flushing.items()
                    for mt in mts]
        for seg, mt in list(self._memtables.items()) + flushing:
            batches = mt.stamped_batches(schema, scan_range)
            if batches:
                out.setdefault(seg, []).extend(batches)
        return out

    async def scan(self, req: ScanRequest,
                   first_plan: Optional[ScanPlan] = None,
                   keep_builtin: bool = False,
                   segment_filter=None) -> AsyncIterator[pa.RecordBatch]:
        schema = self.inner.schema()
        overlay = self._snapshot_overlay(req.range)
        if segment_filter is not None:
            overlay = {s: b for s, b in overlay.items() if segment_filter(s)}
        if not overlay:
            # pure-SST fast path; first_plan is NOT reused — it may
            # predate a flush that just emptied these memtables.
            # Explicit aclose on abandonment: GC-time finalization
            # would let the scan pipeline outlive the query
            it = self.inner.scan(req, keep_builtin=keep_builtin,
                                 segment_filter=segment_filter)
            try:
                async for b in it:
                    yield b
            finally:
                await it.aclose()
            return
        mem_segs = set(overlay)
        # segments with no overlay: the unchanged plan/pushdown path
        it = self.inner.scan(
            req, keep_builtin=keep_builtin,
            segment_filter=lambda s: s not in mem_segs
            and (segment_filter is None or segment_filter(s)))
        try:
            async for b in it:
                yield b
        finally:
            await it.aclose()
        # overlay segments: value-column leaves must apply AFTER the
        # cross-source dedup (filtering first would resurrect
        # overwritten rows), but the PK-only conjunct subtree drops
        # whole PK groups and commutes with last-value dedup — keep its
        # pushdown so the active segment's hybrid reads stay pruned.
        # The full predicate still applies post-dedup in the overlay
        # merge (mem rows of dropped groups fall to the same leaves).
        from horaedb_tpu.ops import And
        from horaedb_tpu.storage import parquet_io

        pk_leaves, _ = parquet_io.conjunct_leaves_ex(
            req.predicate, set(schema.primary_key_names))
        pk_pred = (None if not pk_leaves else
                   pk_leaves[0] if len(pk_leaves) == 1 else And(pk_leaves))
        hybrid_req = ScanRequest(range=req.range, predicate=pk_pred,
                                 projections=req.projections)
        columns = plan_columns(schema, req.projections)
        buffered: dict[int, list] = {}
        seg_iter = self.inner.scan_segments(
            hybrid_req, keep_builtin=True,
            segment_filter=lambda s: s in mem_segs)
        try:
            async for seg, batch in seg_iter:
                if batch is not None:
                    buffered.setdefault(seg, []).append(batch)
                    continue
                with span("memtable_overlay", segment=seg):
                    out = merge_memtable_overlay(
                        schema, buffered.pop(seg, []),
                        overlay.pop(seg, []),
                        req.predicate, columns, keep_builtin)
                if out is not None and out.num_rows:
                    trace_add("memtable_overlay_rows", out.num_rows)
                    yield out
        finally:
            await seg_iter.aclose()
        # segments living only in memtables (no SSTs yet)
        for seg in sorted(overlay):
            with span("memtable_overlay", segment=seg):
                out = merge_memtable_overlay(
                    schema, [], overlay[seg], req.predicate, columns,
                    keep_builtin)
            if out is not None and out.num_rows:
                trace_add("memtable_overlay_rows", out.num_rows)
                yield out

    async def scan_aggregate(self, req: ScanRequest, spec,
                             first_plan: Optional[ScanPlan] = None,
                             top_k=None):
        await self.flush_overlapping(req.range)
        return await self.inner.scan_aggregate(req, spec, top_k=top_k)

    async def plan_query(self, req: ScanRequest, spec=None, top_k=None):
        return await self.inner.plan_query(req, spec=spec, top_k=top_k)

    def execute_plan(self, qp):
        if qp.aggregate is None:
            # the cached first_plan is dropped: it may predate a flush
            # racing this query (one extra manifest lookup, in memory)
            return self.scan(qp.request)

        async def agg():
            # flush overlapping memtables, then REPLAN: the provided
            # plan may predate either this flush or a background one
            # racing the query (aggregate grids read pure SST state)
            await self.flush_overlapping(qp.request.range)
            qp2 = await self.inner.plan_query(qp.request, qp.aggregate,
                                              qp.top_k)
            return await self.inner.execute_plan(qp2)

        return agg()

    # ---- facade plumbing --------------------------------------------------

    def schema(self):
        return self.inner.schema()

    async def compact(self) -> None:
        await self.inner.compact()

    @property
    def value_idxes(self) -> list[int]:
        return self.inner.value_idxes

    def memtable_segments(self) -> set[int]:
        """Segments with acked-but-unflushed rows (live + in-flight
        flushes) — the rollup manager excludes them from coverage so
        buffered rows are always served through the raw/hybrid tail."""
        return ({seg for seg, mt in self._memtables.items() if mt.entries}
                | {seg for seg, mts in self._flushing.items() if mts})

    def oldest_unflushed_seq(self) -> Optional[int]:
        """Min seq across acked-but-unflushed rows; None when fully
        flushed.  The rollup lag watermark must never advance past an
        unflushed (hence unrolled) row's seq, or a stale tier could
        report zero lag."""
        live = list(self._memtables.values()) + [
            mt for mts in self._flushing.values() for mt in mts]
        return min((e.seq for mt in live for e in mt.entries),
                   default=None)

    def ingest_stats(self) -> dict:
        """The /stats surface: buffered state + WAL backlog.  Counts
        include in-flight flushes (still buffered until the SST
        commit)."""
        live = list(self._memtables.values()) + [
            mt for mts in self._flushing.values() for mt in mts]
        rows = sum(mt.rows for mt in live)
        nbytes = sum(mt.bytes for mt in live)
        age = (None if self._last_flush_at is None
               else self._clock() - self._last_flush_at)
        return {"memtable_rows": rows, "memtable_bytes": nbytes,
                "wal_backlog_bytes": self.wal.backlog_bytes,
                "wal_segments": self.wal.segment_count,
                "last_flush_age_s": age}
