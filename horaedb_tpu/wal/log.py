"""Segmented write-ahead log with group commit.

On-disk layout: `{dir}/{id:020d}.wal`, append-only.  Each record is

    [u32 payload_len][u32 crc32(payload)]
    payload = [u64 seq][i64 range_start][i64 range_end][arrow IPC stream]

carrying ONE record batch in the table's USER schema.  The seq is the
write sequence the ingest layer allocated (the same id space SST file
ids come from), so replayed rows keep their position in the `__seq__`
last-value discipline and re-flushing after a crash stays exactly-once.

Group commit: writers enqueue framed records and await; one committer
loop drains the queue (bounded by `max_group_bytes`, padded by a
`max_group_wait` coalescing window), writes the group to the active
segment, issues ONE fsync, then acks every waiter.  Rotation seals the
active segment past `segment_bytes`; `mark_flushed` + `truncate()`
delete sealed segments once every record in them reached an SST.

Durability hooks: every durable transition funnels through `_op()` so
the torture harness can inject a crash at an exact op index (mirroring
the object-store FaultInjectingStore's crash-at-op).  Time never comes
from the wall clock here — callers inject clocks, and replay ordering
relies only on the persisted seqs (the manifest/SST id clock).
"""

from __future__ import annotations

import asyncio
import io
import os
import struct
import zlib
from dataclasses import dataclass, field as dc_field
from typing import Callable, Iterator, Optional

import pyarrow as pa

from horaedb_tpu.common.error import Error, ensure
from horaedb_tpu.common.loops import loops
from horaedb_tpu.storage.types import TimeRange
from horaedb_tpu.utils import op_trace, registry
from horaedb_tpu.wal.config import WalConfig

import logging

logger = logging.getLogger(__name__)

_HEADER = struct.Struct("<II")   # payload_len, crc32
_META = struct.Struct("<Qqq")    # seq, range_start, range_end

# label conventions (docs/observability.md): every per-log series
# carries log=<dir basename> so multi-table nodes separate cleanly;
# each Wal instance binds its children once in __init__
_APPENDS = registry.counter(
    "wal_appends_total", "records appended to the WAL, by log")
_GROUP_COMMITS = registry.counter(
    "wal_group_commits_total", "group commits (one fsync each), by log")
_BYTES_WRITTEN = registry.counter(
    "wal_bytes_written_total", "bytes appended to WAL segments, by log")
_REPLAYED_RECORDS = registry.counter(
    "wal_replayed_records_total", "records recovered by replay, by log")
_REPLAY_CORRUPT = registry.counter(
    "wal_replay_corrupt_records_total",
    "torn/corrupt records skipped during replay")
_TRUNCATED_SEGMENTS = registry.counter(
    "wal_truncated_segments_total",
    "fully-flushed WAL segments deleted, by log")
_BACKLOG = registry.gauge(
    "wal_backlog_bytes",
    "bytes in WAL segments of open logs not yet truncated, by log")
_SEGMENTS = registry.gauge(
    "wal_segments", "live WAL segment files of open logs, by log")


class WalError(Error):
    """A WAL durable op failed (the write was NOT acked)."""


@dataclass
class WalRecord:
    seq: int
    time_range: TimeRange
    batch: pa.RecordBatch


@dataclass
class _Segment:
    id: int
    path: str
    size: int
    # seqs recorded in this segment that no SST covers yet; the segment
    # is deletable once sealed AND this drains empty
    pending: set = dc_field(default_factory=set)
    # highest seq ever committed to this segment (stable across
    # mark_flushed — the replication ack watermark compares against it)
    max_seq: int = 0


def encode_record(seq: int, time_range: TimeRange,
                  batch: pa.RecordBatch) -> bytes:
    sink = pa.BufferOutputStream()
    with pa.ipc.new_stream(sink, batch.schema) as w:
        w.write_batch(batch)
    payload = _META.pack(seq, int(time_range.start),
                         int(time_range.end)) + sink.getvalue().to_pybytes()
    return _HEADER.pack(len(payload), zlib.crc32(payload)) + payload


def decode_records(blob: bytes, path: str = "<wal>") -> Iterator[WalRecord]:
    """Parse one segment's bytes.  Stops at the first torn/corrupt
    record: everything past a bad frame is unframed garbage (a crash
    mid-append), and no record after it can have been acked — group
    commit acks in file order."""
    off = 0
    n = len(blob)
    while off + _HEADER.size <= n:
        length, crc = _HEADER.unpack_from(blob, off)
        start = off + _HEADER.size
        end = start + length
        if length < _META.size or end > n:
            _REPLAY_CORRUPT.inc()
            logger.warning("wal %s: torn record at offset %d", path, off)
            return
        payload = blob[start:end]
        if zlib.crc32(payload) != crc:
            _REPLAY_CORRUPT.inc()
            logger.warning("wal %s: crc mismatch at offset %d", path, off)
            return
        seq, rs, re = _META.unpack_from(payload, 0)
        try:
            with pa.ipc.open_stream(
                    io.BytesIO(payload[_META.size:])) as reader:
                table = reader.read_all()
        except pa.ArrowInvalid:
            _REPLAY_CORRUPT.inc()
            logger.warning("wal %s: bad arrow payload at offset %d",
                           path, off)
            return
        batches = table.combine_chunks().to_batches()
        batch = batches[0] if batches else pa.record_batch(
            [pa.array([], type=f.type) for f in table.schema],
            schema=table.schema)
        yield WalRecord(seq=seq, time_range=TimeRange.new(rs, re),
                        batch=batch)
        off = end


def verify_frames(blob: bytes) -> tuple[int, int, int]:
    """Cheap frame walk (header + crc only, no arrow parse) for the
    replication shipping path: returns (aligned_len, max_seq, count)
    where aligned_len is the byte length of the longest prefix of
    complete, crc-clean frames.  A follower appends only that prefix to
    its mirror, so mirrored segments are always frame-aligned and a
    re-ship resumes exactly at aligned_len."""
    off = 0
    n = len(blob)
    max_seq = 0
    count = 0
    while off + _HEADER.size <= n:
        length, crc = _HEADER.unpack_from(blob, off)
        start = off + _HEADER.size
        end = start + length
        if length < _META.size or end > n:
            break
        payload = blob[start:end]
        if zlib.crc32(payload) != crc:
            break
        seq, _, _ = _META.unpack_from(payload, 0)
        max_seq = max(max_seq, seq)
        count += 1
        off = end
    return off, max_seq, count


def mirror_watermarks(wal_dir: str) -> dict:
    """Per-log highest frame seq under a WAL-layout directory
    (`{wal_dir}/{log}/{id:020d}.wal`), by walking every segment's
    frames (crc-checked; a torn tail just stops the walk).  This is
    the standby election's cold-start fitness source: a follower that
    restarted straight into an outage has empty in-memory progress,
    but its mirror's own bytes still prove exactly how fresh it is."""
    out: dict = {}
    try:
        logs = os.listdir(wal_dir)
    except OSError:
        return out
    for log in logs:
        d = os.path.join(wal_dir, log)
        if not os.path.isdir(d):
            continue
        max_seq = 0
        try:
            names = os.listdir(d)
        except OSError:
            continue
        for name in names:
            if not name.endswith(".wal"):
                continue
            try:
                with open(os.path.join(d, name), "rb") as f:
                    blob = f.read()
            except OSError:
                continue
            _aligned, seq, _count = verify_frames(blob)
            max_seq = max(max_seq, seq)
        out[log] = max_seq
    return out


class Wal:
    """One table's segmented log + group-commit loop.

    All bookkeeping mutates on the event loop; blocking file I/O runs
    in `run_blocking` (default: asyncio.to_thread) with plain arguments
    so threads never touch shared state.
    """

    def __init__(self, wal_dir: str, config: WalConfig,
                 run_blocking: Optional[Callable] = None,
                 on_op: Optional[Callable[[str], None]] = None):
        self.dir = wal_dir
        self.config = config
        lab = {"log": os.path.basename(os.path.normpath(wal_dir)) or "wal"}
        self._log_label = lab["log"]
        self._m_appends = _APPENDS.labels(**lab)
        self._m_group_commits = _GROUP_COMMITS.labels(**lab)
        self._m_bytes_written = _BYTES_WRITTEN.labels(**lab)
        self._m_replayed = _REPLAYED_RECORDS.labels(**lab)
        self._m_truncated = _TRUNCATED_SEGMENTS.labels(**lab)
        self._m_backlog = _BACKLOG.labels(**lab)
        self._m_segments = _SEGMENTS.labels(**lab)
        self._run_blocking = run_blocking or asyncio.to_thread
        self._on_op = on_op
        self._active: Optional[_Segment] = None
        self._active_file = None
        self._sealed: dict[int, _Segment] = {}
        self._next_id = 1
        self._queue: list = []          # [(blob, seq, future), ...]
        self._queue_bytes = 0
        self._wake: Optional[asyncio.Event] = None
        self._commit_task: Optional[asyncio.Task] = None
        self._stopping = False
        self._truncate_lock = asyncio.Lock()
        # serializes group writes against truncate() sealing the active
        # segment (both only run on the event loop, but each awaits
        # blocking file work mid-flight)
        self._commit_lock = asyncio.Lock()
        # replication hook: truncate() deletes a sealed, fully-flushed
        # segment only if retention(segment_id, max_seq) allows it.
        # None = always allow (single-copy behavior, bit-for-bit).  The
        # replication hub points this at its follower-ack watermark so
        # an unshipped segment is never deleted.
        self.retention: Optional[Callable[[int, int], bool]] = None
        # highest seq ever group-committed (or replayed) to this log —
        # the shipping high-watermark followers measure lag against
        self._max_seq = 0
        # highest seq known covered by a committed SST: these seqs are
        # durable in the shared store, so followers need not ship them
        self._flushed_seq = 0

    # ---- open / replay ----------------------------------------------------

    def replay(self) -> list[WalRecord]:
        """Synchronous (call before serving): scan existing segments in
        id order, return every intact record, and register the segments
        as sealed (deletable once their seqs flush).  Appends always go
        to a FRESH segment so a torn tail is never appended past."""
        os.makedirs(self.dir, exist_ok=True)
        out: list[WalRecord] = []
        ids = []
        for name in sorted(os.listdir(self.dir)):
            if not name.endswith(".wal"):
                continue
            try:
                seg_id = int(name[:-4])
            except ValueError:
                continue
            ids.append(seg_id)
            path = os.path.join(self.dir, name)
            with open(path, "rb") as f:
                blob = f.read()
            seg = _Segment(id=seg_id, path=path, size=len(blob))
            for rec in decode_records(blob, path):
                seg.pending.add(rec.seq)
                seg.max_seq = max(seg.max_seq, rec.seq)
                out.append(rec)
            self._max_seq = max(self._max_seq, seg.max_seq)
            self._sealed[seg_id] = seg
            self._m_backlog.inc(seg.size)
            self._m_segments.inc()
        self._next_id = max(ids, default=0) + 1
        self._m_replayed.inc(len(out))
        return out

    def start(self) -> None:
        ensure(self._commit_task is None, "wal already started")
        self._wake = asyncio.Event()
        # fsync rounds are seconds at worst even on sick disks; a
        # committer that stops beating for 30 s is wedged, not busy
        self._commit_task = loops.spawn(
            self._commit_loop, name=f"wal-commit:{self.dir}",
            kind="wal-commit", owner="wal", stall_threshold_s=30.0,
            backlog=lambda: {"queued_records": len(self._queue),
                             "queued_bytes": self._queue_bytes,
                             "backlog_bytes": self.backlog_bytes})

    async def close(self) -> None:
        self._stopping = True
        if self._commit_task is not None:
            self._wake.set()
            try:
                await self._commit_task
            except asyncio.CancelledError:
                pass
            self._commit_task = None
        for _, seq, fut in self._queue:
            if not fut.done():
                fut.set_exception(WalError("wal closed"))
        self._queue = []
        self._queue_bytes = 0
        if self._active_file is not None:
            try:
                self._active_file.close()
            except OSError:
                pass
            self._active_file = None
        # the backlog gauge tracks OPEN logs; the on-disk bytes persist
        # and re-register at the next replay
        for seg in list(self._sealed.values()):
            self._m_backlog.inc(-seg.size)
            self._m_segments.inc(-1)
        if self._active is not None:
            self._m_backlog.inc(-self._active.size)
            self._m_segments.inc(-1)
        self._sealed = {}
        self._active = None

    # ---- append (group commit) -------------------------------------------

    async def append(self, seq: int, time_range: TimeRange,
                     batch: pa.RecordBatch) -> int:
        """Frame + enqueue one record; resolves with the framed size
        AFTER the group's fsync reached disk (the ack point)."""
        ensure(self._commit_task is not None, "wal not started")
        blob = encode_record(seq, time_range, batch)
        fut = asyncio.get_running_loop().create_future()
        self._queue.append((blob, seq, fut))
        self._queue_bytes += len(blob)
        self._wake.set()
        return await fut

    async def _commit_loop(self, hb) -> None:
        cfg = self.config
        while True:
            hb.idle()  # parked on the un-timed wake (healthy silence)
            await self._wake.wait()
            hb.beat()
            self._wake.clear()
            if self._stopping and not self._queue:
                return
            while self._queue:
                hb.beat()
                if (cfg.max_group_wait.seconds > 0
                        and self._queue_bytes < cfg.max_group_bytes
                        and not self._stopping):
                    # coalescing window: let concurrent writers pile on
                    await asyncio.sleep(cfg.max_group_wait.seconds)
                group: list = []
                size = 0
                while self._queue and size < cfg.max_group_bytes:
                    item = self._queue.pop(0)
                    group.append(item)
                    size += len(item[0])
                self._queue_bytes -= size
                try:
                    # one op trace per group-commit fsync round: the
                    # write path's background half, objstore/bytes
                    # attribution included (docs/observability.md)
                    with op_trace("wal_commit", slow_s=5.0,
                                  log=self._log_label,
                                  records=len(group), bytes=size):
                        await self._commit_group(group, size)
                    hb.ok()
                except asyncio.CancelledError:
                    for _, _, fut in group:
                        if not fut.done():
                            fut.set_exception(WalError("wal cancelled"))
                    self._quarantine_active_nowait()
                    raise
                except Exception as exc:  # noqa: BLE001 — fail the group
                    hb.error(exc)
                    for _, _, fut in group:
                        if not fut.done():
                            fut.set_exception(
                                exc if isinstance(exc, WalError)
                                else WalError(f"wal append failed: {exc}"))
                    # the failed write may have left a TORN frame at the
                    # active segment's tail; appending past it would put
                    # later ACKED groups behind bytes replay cannot cross
                    # (decode stops at the first bad frame), so the next
                    # group must start a fresh segment
                    await self._quarantine_active()
            if self._stopping:
                return

    async def _commit_group(self, group: list, size: int) -> None:
        async with self._commit_lock:
            await self._commit_group_locked(group, size)

    async def _commit_group_locked(self, group: list, size: int) -> None:
        if self._active is None or (
                self._active.size + size > self.config.segment_bytes
                and self._active.size > 0):
            await self._rotate()
        seg = self._active
        f = self._active_file
        blobs = [blob for blob, _, _ in group]
        await self._run_blocking(self._write_group_blocking, f, blobs)
        seg.size += size
        for blob, seq, _ in group:
            seg.pending.add(seq)
            seg.max_seq = max(seg.max_seq, seq)
        self._max_seq = max(self._max_seq, seg.max_seq)
        self._m_appends.inc(len(group))
        self._m_group_commits.inc()
        self._m_bytes_written.inc(size)
        self._m_backlog.inc(size)
        for blob, _, fut in group:
            if not fut.done():
                fut.set_result(len(blob))

    def _op(self, op: str) -> None:
        if self._on_op is not None:
            self._on_op(op)

    def _write_group_blocking(self, f, blobs: list) -> None:
        self._op("append")
        for blob in blobs:
            f.write(blob)
        f.flush()
        self._op("fsync")
        os.fsync(f.fileno())
        self._op("acked")

    def _seal_active(self):
        """Shared quarantine bookkeeping after a failed group write:
        seal the active segment so its intact prefix (every previously-
        fsynced record) stays replayable and truncatable, and no future
        append lands past a possibly-torn tail frame.  Returns the file
        handle for the caller to close (awaited or direct)."""
        if self._active is None:
            return None
        seg, f = self._active, self._active_file
        self._active = None
        self._active_file = None
        self._sealed[seg.id] = seg
        return f

    async def _quarantine_active(self) -> None:
        f = self._seal_active()
        if f is not None:
            try:
                await self._run_blocking(f.close)
            except OSError:
                pass

    def _quarantine_active_nowait(self) -> None:
        """Cancellation-path twin (cannot await mid-unwind)."""
        f = self._seal_active()
        if f is not None:
            try:
                f.close()
            except OSError:
                pass

    async def _rotate(self) -> None:
        """Seal the active segment and open a fresh one (the new file
        plus a directory fsync so the entry itself is durable)."""
        if self._active is not None:
            old_file = self._active_file
            self._sealed[self._active.id] = self._active
            self._active = None
            self._active_file = None
            await self._run_blocking(old_file.close)
        seg_id = self._next_id
        self._next_id += 1
        path = os.path.join(self.dir, f"{seg_id:020d}.wal")
        f = await self._run_blocking(self._open_segment_blocking, path)
        self._active = _Segment(id=seg_id, path=path, size=0)
        self._active_file = f
        self._m_segments.inc()

    def _open_segment_blocking(self, path: str):
        os.makedirs(self.dir, exist_ok=True)
        f = open(path, "ab")
        dir_fd = os.open(self.dir, os.O_RDONLY)
        try:
            os.fsync(dir_fd)
        finally:
            os.close(dir_fd)
        return f

    # ---- flush / truncation ----------------------------------------------

    def mark_flushed(self, seqs) -> None:
        """Record that these seqs are covered by a committed SST; their
        segments become truncatable once fully drained and sealed."""
        remaining = set(seqs)
        for seg in self._sealed.values():
            if seg.pending:
                seg.pending -= remaining
        if self._active is not None and self._active.pending:
            self._active.pending -= remaining
        self._recompute_flushed()

    def _recompute_flushed(self) -> None:
        """Advance `_flushed_seq` to the contiguous SST-covered PREFIX:
        the highest seq with every committed seq at or below it covered
        by a committed SST.  Memtables are per time-segment and flush
        out of order while sharing this log with interleaved seqs, so a
        max over any one flushed batch would overshoot — reporting seqs
        flushed while older ones are still only WAL-resident, which a
        follower would read as \"caught up\" over rows a failover would
        lose.  Derived from the pending sets: anything below every
        still-pending seq has been flushed out of them."""
        floor = self._max_seq
        for seg in self._sealed.values():
            if seg.pending:
                floor = min(floor, min(seg.pending) - 1)
        if self._active is not None and self._active.pending:
            floor = min(floor, min(self._active.pending) - 1)
        self._flushed_seq = max(self._flushed_seq, floor)

    async def truncate(self) -> int:
        """Delete sealed, fully-flushed segments.  SST + manifest commit
        MUST precede the mark_flushed that makes a segment deletable —
        that ordering is the crash-safety invariant (docs/robustness.md).
        Returns the number of segments deleted."""
        async with self._truncate_lock:
            # a fully-drained, non-empty ACTIVE segment seals too: a
            # complete flush returns the steady-state backlog to zero
            # (the commit lock keeps a mid-flight group off the file)
            if (self._active is not None and self._active.size > 0
                    and not self._active.pending and not self._queue):
                async with self._commit_lock:
                    if (self._active is not None
                            and self._active.size > 0
                            and not self._active.pending
                            and not self._queue):
                        seg, f = self._active, self._active_file
                        self._active = None
                        self._active_file = None
                        self._sealed[seg.id] = seg
                        await self._run_blocking(f.close)
            dead = [seg for seg in self._sealed.values()
                    if not seg.pending
                    and (self.retention is None
                         or self.retention(seg.id, seg.max_seq))]
            for seg in dead:
                await self._run_blocking(self._unlink_blocking, seg.path)
                self._sealed.pop(seg.id, None)
                self._m_truncated.inc()
                self._m_backlog.inc(-seg.size)
                self._m_segments.inc(-1)
            return len(dead)

    def _unlink_blocking(self, path: str) -> None:
        self._op("truncate")
        try:
            os.unlink(path)
        except FileNotFoundError:
            pass
        dir_fd = os.open(self.dir, os.O_RDONLY)
        try:
            os.fsync(dir_fd)
        finally:
            os.close(dir_fd)

    # ---- introspection ----------------------------------------------------

    @property
    def backlog_bytes(self) -> int:
        total = sum(s.size for s in self._sealed.values())
        if self._active is not None:
            total += self._active.size
        return total

    @property
    def segment_count(self) -> int:
        return len(self._sealed) + (1 if self._active is not None else 0)

    @property
    def high_watermark(self) -> int:
        """Highest seq durably committed to this log (0 = none).  The
        shipping plane's per-log progress marker: a follower that has
        mirrored through this seq is fully caught up."""
        return self._max_seq

    @property
    def flushed_seq(self) -> int:
        """The contiguous SST-covered prefix (0 = none): EVERY
        committed seq at or below this is covered by a committed SST,
        so a follower counts them all as caught up without shipping —
        their segments may already be truncated.  Out-of-order segment
        flushes do not advance it past a still-WAL-resident seq."""
        return self._flushed_seq

    def segments(self) -> list[dict]:
        """Durable segment listing for the shipping plane, id-ordered:
        {id, size, sealed, max_seq}.  Sizes count only fully-committed
        group bytes (seg.size advances after the group fsync), so a
        tail read bounded by `size` never sees a torn frame."""
        out = []
        for seg in self._sealed.values():
            out.append({"id": seg.id, "size": seg.size, "sealed": True,
                        "max_seq": seg.max_seq})
        if self._active is not None:
            seg = self._active
            out.append({"id": seg.id, "size": seg.size, "sealed": False,
                        "max_seq": seg.max_seq})
        out.sort(key=lambda s: s["id"])
        return out

    async def read_tail(self, segment_id: int, offset: int,
                        max_bytes: int) -> Optional[tuple[bytes, bool]]:
        """Frame-level tail read: up to `max_bytes` of segment
        `segment_id` starting at `offset`, capped at the committed size
        snapshot (never into a possibly-torn uncommitted tail).
        Returns (blob, sealed) — blob is b"" when already caught up —
        or None when the segment no longer exists (truncated; the
        follower drops its mirror copy too).  Callers must pass offsets
        that sit on frame boundaries (0, or a previous read's offset +
        verify_frames(...)[0]) for the result to stay frame-aligned."""
        ensure(offset >= 0 and max_bytes > 0,
               "read_tail: offset must be >= 0 and max_bytes > 0")
        seg = self._sealed.get(segment_id)
        sealed = seg is not None
        if seg is None and self._active is not None \
                and self._active.id == segment_id:
            seg = self._active
        if seg is None:
            return None
        # snapshot the committed size ON the event loop before handing
        # off to a thread: seg.size only moves forward, and bytes below
        # it are fsynced whole frames
        end = min(seg.size, offset + max_bytes)
        if end <= offset:
            return b"", sealed
        blob = await self._run_blocking(
            self._read_range_blocking, seg.path, offset, end - offset)
        return blob, sealed

    def _read_range_blocking(self, path: str, offset: int,
                             length: int) -> bytes:
        with open(path, "rb") as f:
            f.seek(offset)
            return f.read(length)
