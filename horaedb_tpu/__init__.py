"""horaedb-tpu: a TPU-native time-series storage & query framework.

A from-scratch rebuild of Apache HoraeDB's metric-engine architecture
(reference: /root/reference, surveyed in SURVEY.md) designed TPU-first:

- Host engine (Python/asyncio + C++ hot paths): manifest, SST lifecycle,
  time-window compaction, object-store I/O, Arrow ingestion.
- Compute core (JAX/XLA/Pallas): the columnar scan path -- predicate
  filtering, primary-key merge/dedup, time-bucketed downsampling -- runs as
  compiled kernels over HBM-resident columnar batches, sharded across chips
  by time segment with ICI collectives.

Layout mirrors the reference's crate graph (SURVEY.md section 1):
  common/        errors, human-readable durations/sizes   (ref: src/common)
  objstore/      object-storage abstraction               (ref: object_store crate)
  storage/       TimeMergeStorage engine                  (ref: src/storage)
  ops/           JAX/Pallas physical operators            (ref: DataFusion layer)
  parallel/      mesh / shard_map multi-chip execution    (new, TPU-native)
  metric_engine/ Prometheus-style metric layer            (ref: src/metric_engine + RFC)
  server/        HTTP server + config                     (ref: src/server)
"""

__version__ = "0.1.0"
