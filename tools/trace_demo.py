#!/usr/bin/env python
"""`make trace-demo`: start a local server, write + query a metric,
then fetch the query's trace and pretty-print its span tree.

With `--ops`, also exercise the BACKGROUND plane — force SSTs, trigger
a compaction and a rollup maintenance pass — then pretty-print the most
recent op traces (/debug/traces?kind=op) alongside the query tree.

Usage: python tools/trace_demo.py [--port N] [--ops]
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _print_tree(node: dict, depth: int = 0) -> None:
    pad = "  " * depth
    fields = " ".join(f"{k}={v}" for k, v in
                      (node.get("fields") or {}).items())
    status = node.get("status", "?")
    mark = "" if status == "ok" else f" [{status.upper()}]"
    print(f"{pad}{node.get('name', '?'):<28s} "
          f"{node.get('duration_ms', 0):>9.2f} ms{mark}"
          f"{('  ' + fields) if fields else ''}")
    for child in node.get("children", []):
        _print_tree(child, depth + 1)


async def _print_op_trace(s, base: str, timeout, op: str,
                          deadline_s: float = 20.0) -> None:
    """Poll /debug/traces?op= until the newest trace of that op shows,
    then print its tree (ops complete asynchronously to the admin
    calls that provoked them)."""
    t_end = asyncio.get_running_loop().time() + deadline_s
    trace_id = None
    while asyncio.get_running_loop().time() < t_end:
        async with s.get(f"{base}/debug/traces?op={op}&limit=1",
                         timeout=timeout) as r:
            traces = (await r.json())["traces"]
        if traces:
            trace_id = traces[0]["trace_id"]
            break
        await asyncio.sleep(0.2)
    if trace_id is None:
        print(f"\n== no {op} op trace appeared within {deadline_s}s ==")
        return
    async with s.get(f"{base}/debug/traces/{trace_id}",
                     timeout=timeout) as r:
        trace = await r.json()
    print(f"\n== op trace: {op} ({trace_id}, "
          f"status={trace['status']}, slow={trace.get('slow')}) ==")
    _print_tree(trace["tree"])
    counters = {k: round(v, 2)
                for k, v in sorted(trace.get("counters", {}).items())}
    if counters:
        print(json.dumps(counters, indent=2))


async def main(port: int, ops: bool = False) -> int:
    import aiohttp

    from horaedb_tpu.server.config import ServerConfig, load_config
    from horaedb_tpu.server.main import run_server

    t0 = 1_700_000_000_000
    with tempfile.TemporaryDirectory(prefix="trace-demo-") as tmp:
        config = load_config(None)
        config = ServerConfig(
            port=port, test=config.test, admission=config.admission,
            breaker=config.breaker, wal=config.wal, trace=config.trace,
            metric_engine=config.metric_engine, rollup=config.rollup,
            watchdog=config.watchdog, meta=config.meta)
        config.metric_engine.object_store.data_dir = tmp
        if ops:
            # make the background plane fire fast: eager compaction
            # (2 small SSTs qualify) and standing rollups on the demo
            # metric
            sched = config.metric_engine.time_merge_storage.scheduler
            sched.input_sst_min_num = 2
            config.rollup.enabled = True
            config.rollup.specs = ["demo.cpu"]
        ready = asyncio.Event()
        server = asyncio.create_task(run_server(config, ready=ready))
        await asyncio.wait_for(ready.wait(), 30)
        base = f"http://127.0.0.1:{port}"
        async with aiohttp.ClientSession() as s:
            timeout = aiohttp.ClientTimeout(total=30)
            samples = [{"name": "demo.cpu",
                        "labels": {"host": f"h{i % 4}"},
                        "timestamp": t0 + i * 1000, "value": float(i)}
                       for i in range(400)]
            async with s.post(f"{base}/write",
                              json={"samples": samples},
                              timeout=timeout) as r:
                assert r.status == 200, await r.text()
                print(f"write trace:  {r.headers.get('X-Trace-Id')}  "
                      f"({r.headers.get('X-Trace-Summary')})")
            async with s.post(f"{base}/query", json={
                    "metric": "demo.cpu", "start": t0,
                    "end": t0 + 400_000, "bucket_ms": 60_000},
                    timeout=timeout) as r:
                assert r.status == 200, await r.text()
                trace_id = r.headers["X-Trace-Id"]
                print(f"query trace:  {trace_id}  "
                      f"({r.headers.get('X-Trace-Summary')})")
            async with s.get(f"{base}/debug/traces/{trace_id}",
                             timeout=timeout) as r:
                assert r.status == 200, await r.text()
                trace = await r.json()
            print(f"\n== span tree for {trace_id} "
                  f"(status={trace['status']}, "
                  f"slow={trace.get('slow')}) ==")
            _print_tree(trace["tree"])
            counters = {k: round(v, 2) for k, v in
                        sorted(trace.get("counters", {}).items())}
            print("\n== per-trace counters ==")
            print(json.dumps(counters, indent=2))
            if ops:
                # second SST in the same segment, then provoke the two
                # showcase ops: a compaction rewrite and a roll pass
                samples2 = [{"name": "demo.cpu",
                             "labels": {"host": f"h{i % 4}"},
                             "timestamp": t0 + i * 1000 + 500,
                             "value": float(i) * 2}
                            for i in range(400)]
                async with s.post(f"{base}/write",
                                  json={"samples": samples2},
                                  timeout=timeout) as r:
                    assert r.status == 200, await r.text()
                async with s.get(f"{base}/compact", timeout=timeout) as r:
                    assert r.status == 200, await r.text()
                async with s.post(f"{base}/admin/rollups",
                                  json={"roll": True},
                                  timeout=timeout) as r:
                    assert r.status == 200, await r.text()
                await _print_op_trace(s, base, timeout, "compaction")
                await _print_op_trace(s, base, timeout, "rollup_pass",
                                      deadline_s=5.0)
                async with s.get(f"{base}/debug/tasks",
                                 timeout=timeout) as r:
                    tasks = await r.json()
                print("\n== /debug/tasks (background loops) ==")
                for lp in tasks["loops"]:
                    print(f"  {lp['kind']:<18s} alive={lp['alive']} "
                          f"hb_age={lp['heartbeat_age_s']:>7.3f}s "
                          f"stalled={lp['stalled']} "
                          f"errs={lp['consecutive_errors']}")
        server.cancel()
        try:
            await server
        except (asyncio.CancelledError, Exception):
            pass
    return 0


if __name__ == "__main__":
    parser = argparse.ArgumentParser("trace-demo")
    parser.add_argument("--port", type=int, default=5123)
    parser.add_argument("--ops", action="store_true",
                        help="also provoke + pretty-print background "
                             "op traces (compaction, roll pass) and "
                             "the /debug/tasks loop table")
    args = parser.parse_args()
    sys.exit(asyncio.run(main(args.port, ops=args.ops)))
