#!/usr/bin/env python
"""`make trace-demo`: start a local server, write + query a metric,
then fetch the query's trace and pretty-print its span tree.

Usage: python tools/trace_demo.py [--port N]
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _print_tree(node: dict, depth: int = 0) -> None:
    pad = "  " * depth
    fields = " ".join(f"{k}={v}" for k, v in
                      (node.get("fields") or {}).items())
    status = node.get("status", "?")
    mark = "" if status == "ok" else f" [{status.upper()}]"
    print(f"{pad}{node.get('name', '?'):<28s} "
          f"{node.get('duration_ms', 0):>9.2f} ms{mark}"
          f"{('  ' + fields) if fields else ''}")
    for child in node.get("children", []):
        _print_tree(child, depth + 1)


async def main(port: int) -> int:
    import aiohttp

    from horaedb_tpu.server.config import ServerConfig, load_config
    from horaedb_tpu.server.main import run_server

    t0 = 1_700_000_000_000
    with tempfile.TemporaryDirectory(prefix="trace-demo-") as tmp:
        config = load_config(None)
        config = ServerConfig(
            port=port, test=config.test, admission=config.admission,
            breaker=config.breaker, wal=config.wal, trace=config.trace,
            metric_engine=config.metric_engine)
        config.metric_engine.object_store.data_dir = tmp
        ready = asyncio.Event()
        server = asyncio.create_task(run_server(config, ready=ready))
        await asyncio.wait_for(ready.wait(), 30)
        base = f"http://127.0.0.1:{port}"
        async with aiohttp.ClientSession() as s:
            timeout = aiohttp.ClientTimeout(total=30)
            samples = [{"name": "demo.cpu",
                        "labels": {"host": f"h{i % 4}"},
                        "timestamp": t0 + i * 1000, "value": float(i)}
                       for i in range(400)]
            async with s.post(f"{base}/write",
                              json={"samples": samples},
                              timeout=timeout) as r:
                assert r.status == 200, await r.text()
                print(f"write trace:  {r.headers.get('X-Trace-Id')}  "
                      f"({r.headers.get('X-Trace-Summary')})")
            async with s.post(f"{base}/query", json={
                    "metric": "demo.cpu", "start": t0,
                    "end": t0 + 400_000, "bucket_ms": 60_000},
                    timeout=timeout) as r:
                assert r.status == 200, await r.text()
                trace_id = r.headers["X-Trace-Id"]
                print(f"query trace:  {trace_id}  "
                      f"({r.headers.get('X-Trace-Summary')})")
            async with s.get(f"{base}/debug/traces/{trace_id}",
                             timeout=timeout) as r:
                assert r.status == 200, await r.text()
                trace = await r.json()
        print(f"\n== span tree for {trace_id} "
              f"(status={trace['status']}, slow={trace.get('slow')}) ==")
        _print_tree(trace["tree"])
        counters = {k: round(v, 2)
                    for k, v in sorted(trace.get("counters", {}).items())}
        print("\n== per-trace counters ==")
        print(json.dumps(counters, indent=2))
        server.cancel()
        try:
            await server
        except (asyncio.CancelledError, Exception):
            pass
    return 0


if __name__ == "__main__":
    parser = argparse.ArgumentParser("trace-demo")
    parser.add_argument("--port", type=int, default=5123)
    sys.exit(asyncio.run(main(parser.parse_args().port)))
