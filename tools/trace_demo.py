#!/usr/bin/env python
"""`make trace-demo`: start a local server, write + query a metric,
then fetch the query's trace and pretty-print its span tree.

With `--ops`, also exercise the BACKGROUND plane — force SSTs, trigger
a compaction and a rollup maintenance pass — then pretty-print the most
recent op traces (/debug/traces?kind=op) alongside the query tree.

With `--device` (`make trace-demo-device`), exercise the DEVICE plane
instead: drive a cold fused mesh-decode aggregate directly against a
throwaway CloudObjectStorage, repeat it warm, and pretty-print the
compile/dispatch/exec/transfer attribution the profiler collected —
the same tables `GET /debug/device` serves, plus the per-trace device
twins showing the warm repeat paid nothing.

Usage: python tools/trace_demo.py [--port N] [--ops | --device]
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _print_tree(node: dict, depth: int = 0) -> None:
    pad = "  " * depth
    fields = " ".join(f"{k}={v}" for k, v in
                      (node.get("fields") or {}).items())
    status = node.get("status", "?")
    mark = "" if status == "ok" else f" [{status.upper()}]"
    print(f"{pad}{node.get('name', '?'):<28s} "
          f"{node.get('duration_ms', 0):>9.2f} ms{mark}"
          f"{('  ' + fields) if fields else ''}")
    for child in node.get("children", []):
        _print_tree(child, depth + 1)


async def _print_op_trace(s, base: str, timeout, op: str,
                          deadline_s: float = 20.0) -> None:
    """Poll /debug/traces?op= until the newest trace of that op shows,
    then print its tree (ops complete asynchronously to the admin
    calls that provoked them)."""
    t_end = asyncio.get_running_loop().time() + deadline_s
    trace_id = None
    while asyncio.get_running_loop().time() < t_end:
        async with s.get(f"{base}/debug/traces?op={op}&limit=1",
                         timeout=timeout) as r:
            traces = (await r.json())["traces"]
        if traces:
            trace_id = traces[0]["trace_id"]
            break
        await asyncio.sleep(0.2)
    if trace_id is None:
        print(f"\n== no {op} op trace appeared within {deadline_s}s ==")
        return
    async with s.get(f"{base}/debug/traces/{trace_id}",
                     timeout=timeout) as r:
        trace = await r.json()
    print(f"\n== op trace: {op} ({trace_id}, "
          f"status={trace['status']}, slow={trace.get('slow')}) ==")
    _print_tree(trace["tree"])
    counters = {k: round(v, 2)
                for k, v in sorted(trace.get("counters", {}).items())}
    if counters:
        print(json.dumps(counters, indent=2))


def _device_twins(trace) -> dict:
    return {k: round(v, 2) for k, v in sorted(trace.counters.items())
            if k.startswith("stage_device_") or k.startswith("device_")}


async def device_main() -> int:
    """The --device leg: cold fused mesh-decode round, warm repeat,
    then the profiler's attribution tables (docs/observability.md,
    device plane)."""
    import random

    # the bit-identity convention: aggregate with the XLA window
    # kernel so the fused dispatch actually runs on the device path
    os.environ["HORAEDB_HOST_AGG"] = "0"

    from horaedb_tpu.common import ReadableDuration, deviceprof
    from horaedb_tpu.common import runtimes as runtimes_mod
    from horaedb_tpu.objstore import MemoryObjectStore
    from horaedb_tpu.storage.config import (
        StorageConfig,
        ThreadsConfig,
        from_dict,
    )
    from horaedb_tpu.storage.read import AggregateSpec, ScanRequest
    from horaedb_tpu.storage.storage import CloudObjectStorage, WriteRequest
    from horaedb_tpu.storage.types import TimeRange
    from horaedb_tpu.utils import tracing

    import pyarrow as pa

    segment_ms = 3_600_000
    schema = pa.schema([("k", pa.string()), ("ts", pa.int64()),
                        ("v", pa.float64())])
    cfg = from_dict(StorageConfig, {
        "scheduler": {"schedule_interval": "1h", "input_sst_min_num": 2},
        "scan": {"mesh": {"enabled": True},
                 "decode": {"mode": "device"}},
    })
    cfg.manifest.merge_interval = ReadableDuration.parse("1h")
    cfg.scrub.interval = ReadableDuration.parse("1h")
    rt = runtimes_mod.from_config(ThreadsConfig())
    s = await CloudObjectStorage.open(
        "db", segment_ms, MemoryObjectStore(), schema, 2, cfg,
        runtimes=rt)
    try:
        rng = random.Random(1337)
        for seg in range(3):
            rows = [(f"k{rng.randint(0, 7)}",
                     seg * segment_ms + rng.randrange(
                         0, segment_ms - 1000, 250),
                     float(rng.randint(0, 10**6))) for _ in range(300)]
            lo = min(r[1] for r in rows)
            hi = max(r[1] for r in rows) + 1
            k, t, v = zip(*rows)
            b = pa.record_batch(
                [pa.array(list(k)), pa.array(list(t), type=pa.int64()),
                 pa.array(list(v), type=pa.float64())], schema=schema)
            await s.write(WriteRequest(b, TimeRange.new(lo, hi)))
        s.reader.scan_cache.clear()
        s.reader.encoded_cache.clear()
        s.reader.parts_memo.clear()
        deviceprof.profiler.clear()
        tracing.recorder.configure(enabled=True, sample_rate=1.0)

        spec = AggregateSpec(
            group_col="k", ts_col="ts", value_col="v", range_start=0,
            bucket_ms=60_000,
            num_buckets=-(-(3 * segment_ms) // 60_000),
            which=("avg", "max", "last"))
        req = ScanRequest(range=TimeRange.new(0, 3 * segment_ms))

        async def traced_scan(name):
            trace = tracing.recorder.start(name)
            t0 = asyncio.get_running_loop().time()
            with tracing.trace_scope(trace):
                await s.scan_aggregate(req, spec)
            wall_ms = (asyncio.get_running_loop().time() - t0) * 1e3
            tracing.recorder.finish(trace)
            return trace, wall_ms

        cold, cold_ms = await traced_scan("/scan-cold")
        warm, warm_ms = await traced_scan("/scan-warm")

        snap = deviceprof.profiler.snapshot()
        print("== compile ledger (cold mesh-decode + warm repeat) ==")
        hdr = (f"{'fn':<34s} {'comp':>4s} {'comp_ms':>8s} "
               f"{'disp':>4s} {'disp_ms':>8s} {'exec':>4s} "
               f"{'exec_ms':>8s}")
        print(hdr)
        for rec in snap["fns"]:
            if not (rec["compiles"] or rec["dispatches"] or rec["execs"]):
                continue
            print(f"{rec['fn']:<34.34s} {rec['compiles']:>4d} "
                  f"{rec['compile_seconds'] * 1e3:>8.1f} "
                  f"{rec['dispatches']:>4d} "
                  f"{rec['dispatch_seconds'] * 1e3:>8.1f} "
                  f"{rec['execs']:>4d} "
                  f"{rec['exec_seconds'] * 1e3:>8.1f}")
        print("\n== transfers ==")
        for d, t in snap["transfer"].items():
            print(f"  {d}: {t['bytes']:>10d} B in {t['count']:>3d} "
                  f"transfers ({t['seconds'] * 1e3:.2f} ms)")
        if snap["rounds"]:
            print("\n== mesh round timeline ==")
            for r in snap["rounds"]:
                rows_s = ""
                if "row_imbalance" in r:
                    rows_s = (f" imbalance={r['row_imbalance']} "
                              f"shard_rows={r['shard_rows']}")
                print(f"  {r['kind']:<12s} fill={r['fill_ratio']} "
                      f"({r['slots']}/{r['capacity']}) "
                      f"pad_rows={r['padding_rows']} "
                      f"stack_hit={r['stack_hit']}{rows_s}")
        print(f"\n== cold scan ({cold_ms:.1f} ms wall) device twins ==")
        print(json.dumps(_device_twins(cold), indent=2))
        print(f"\n== warm repeat ({warm_ms:.1f} ms wall) device twins "
              f"(memo-served: expect none) ==")
        print(json.dumps(_device_twins(warm), indent=2))
    finally:
        await s.close()
        rt.close()
    return 0


async def main(port: int, ops: bool = False) -> int:
    import aiohttp

    from horaedb_tpu.server.config import ServerConfig, load_config
    from horaedb_tpu.server.main import run_server

    t0 = 1_700_000_000_000
    with tempfile.TemporaryDirectory(prefix="trace-demo-") as tmp:
        config = load_config(None)
        config = ServerConfig(
            port=port, test=config.test, admission=config.admission,
            breaker=config.breaker, wal=config.wal, trace=config.trace,
            metric_engine=config.metric_engine, rollup=config.rollup,
            watchdog=config.watchdog, meta=config.meta)
        config.metric_engine.object_store.data_dir = tmp
        if ops:
            # make the background plane fire fast: eager compaction
            # (2 small SSTs qualify) and standing rollups on the demo
            # metric
            sched = config.metric_engine.time_merge_storage.scheduler
            sched.input_sst_min_num = 2
            config.rollup.enabled = True
            config.rollup.specs = ["demo.cpu"]
        ready = asyncio.Event()
        server = asyncio.create_task(run_server(config, ready=ready))
        await asyncio.wait_for(ready.wait(), 30)
        base = f"http://127.0.0.1:{port}"
        async with aiohttp.ClientSession() as s:
            timeout = aiohttp.ClientTimeout(total=30)
            samples = [{"name": "demo.cpu",
                        "labels": {"host": f"h{i % 4}"},
                        "timestamp": t0 + i * 1000, "value": float(i)}
                       for i in range(400)]
            async with s.post(f"{base}/write",
                              json={"samples": samples},
                              timeout=timeout) as r:
                assert r.status == 200, await r.text()
                print(f"write trace:  {r.headers.get('X-Trace-Id')}  "
                      f"({r.headers.get('X-Trace-Summary')})")
            async with s.post(f"{base}/query", json={
                    "metric": "demo.cpu", "start": t0,
                    "end": t0 + 400_000, "bucket_ms": 60_000},
                    timeout=timeout) as r:
                assert r.status == 200, await r.text()
                trace_id = r.headers["X-Trace-Id"]
                print(f"query trace:  {trace_id}  "
                      f"({r.headers.get('X-Trace-Summary')})")
            async with s.get(f"{base}/debug/traces/{trace_id}",
                             timeout=timeout) as r:
                assert r.status == 200, await r.text()
                trace = await r.json()
            print(f"\n== span tree for {trace_id} "
                  f"(status={trace['status']}, "
                  f"slow={trace.get('slow')}) ==")
            _print_tree(trace["tree"])
            counters = {k: round(v, 2) for k, v in
                        sorted(trace.get("counters", {}).items())}
            print("\n== per-trace counters ==")
            print(json.dumps(counters, indent=2))
            if ops:
                # second SST in the same segment, then provoke the two
                # showcase ops: a compaction rewrite and a roll pass
                samples2 = [{"name": "demo.cpu",
                             "labels": {"host": f"h{i % 4}"},
                             "timestamp": t0 + i * 1000 + 500,
                             "value": float(i) * 2}
                            for i in range(400)]
                async with s.post(f"{base}/write",
                                  json={"samples": samples2},
                                  timeout=timeout) as r:
                    assert r.status == 200, await r.text()
                async with s.get(f"{base}/compact", timeout=timeout) as r:
                    assert r.status == 200, await r.text()
                async with s.post(f"{base}/admin/rollups",
                                  json={"roll": True},
                                  timeout=timeout) as r:
                    assert r.status == 200, await r.text()
                await _print_op_trace(s, base, timeout, "compaction")
                await _print_op_trace(s, base, timeout, "rollup_pass",
                                      deadline_s=5.0)
                async with s.get(f"{base}/debug/tasks",
                                 timeout=timeout) as r:
                    tasks = await r.json()
                print("\n== /debug/tasks (background loops) ==")
                for lp in tasks["loops"]:
                    print(f"  {lp['kind']:<18s} alive={lp['alive']} "
                          f"hb_age={lp['heartbeat_age_s']:>7.3f}s "
                          f"stalled={lp['stalled']} "
                          f"errs={lp['consecutive_errors']}")
        server.cancel()
        try:
            await server
        except (asyncio.CancelledError, Exception):
            pass
    return 0


if __name__ == "__main__":
    parser = argparse.ArgumentParser("trace-demo")
    parser.add_argument("--port", type=int, default=5123)
    parser.add_argument("--ops", action="store_true",
                        help="also provoke + pretty-print background "
                             "op traces (compaction, roll pass) and "
                             "the /debug/tasks loop table")
    parser.add_argument("--device", action="store_true",
                        help="device-plane demo: cold fused "
                             "mesh-decode round + warm repeat, then "
                             "the compile/dispatch/exec/transfer "
                             "attribution tables")
    args = parser.parse_args()
    if args.device:
        sys.exit(asyncio.run(device_main()))
    sys.exit(asyncio.run(main(args.port, ops=args.ops)))
