#!/usr/bin/env python
"""Self-driving failover bench runner that ALWAYS records a result.

Runs `BENCH_CONFIG=21` (the self-driving failover SLO harness: the
harness only kills the primary at mid-leg — a StandbyMonitor detects
the expired lease, wins the election, replays its mirror, and brings
up the new serving node with ZERO harness promote() calls) in a
subprocess under a hard timeout and writes
`bench_results/failover_rNN.json` (next free index) with an explicit
`status` of "ok" | "timeout" | "error" — on EVERY outcome, including
the process being killed.  rc=124 (an outer `timeout(1)`) classifies
as "timeout" too: the history must distinguish "timed out" from
"never ran".

`ok` requires BOTH bars: bar_zero_loss (no acked write lost across
the election) and bar_failover_bound (detection + election + replay
lands inside lease TTL + the worst-case jittered grace window +
fixed slack).

Usage:
    python tools/failover_run.py [--rows 100000] [--iters 10]
                                 [--timeout 300] [--out PATH]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def next_record_path() -> str:
    results = os.path.join(ROOT, "bench_results")
    os.makedirs(results, exist_ok=True)
    taken = set()
    for p in glob.glob(os.path.join(results, "failover_r*.json")):
        m = re.search(r"failover_r(\d+)\.json$", p)
        if m:
            taken.add(int(m.group(1)))
    n = 1
    while n in taken:
        n += 1
    return os.path.join(results, f"failover_r{n:02d}.json")


def run(rows: int, iters: int, timeout_s: float) -> dict:
    cmd = [sys.executable, "bench.py"]
    env = dict(os.environ)
    env["BENCH_CONFIG"] = "21"
    env.setdefault("BENCH_ROWS", str(rows))
    env.setdefault("BENCH_ITERS", str(iters))
    t0 = time.perf_counter()
    record = {"config": 21, "rows": rows, "iters": iters,
              "timeout_s": timeout_s, "cmd": " ".join(cmd)}
    try:
        proc = subprocess.run(cmd, cwd=ROOT, capture_output=True,
                              text=True, timeout=timeout_s, env=env)
        record["rc"] = proc.returncode
        record["ok"] = proc.returncode == 0
        record["status"] = ("ok" if proc.returncode == 0 else
                            "timeout" if proc.returncode == 124 else
                            "error")
        record["tail"] = (proc.stderr or proc.stdout or "")[-2000:]
        if proc.returncode == 0:
            # bench.py prints ONE result JSON on its last stdout line
            for line in reversed(proc.stdout.strip().splitlines()):
                try:
                    record["result"] = json.loads(line)
                    break
                except json.JSONDecodeError:
                    continue
            result = record.get("result") or {}
            record["ok"] = bool(result.get("bar_zero_loss", False)
                                and result.get("bar_failover_bound",
                                               False))
            if not record["ok"]:
                record["status"] = "error"
    except subprocess.TimeoutExpired as exc:
        # a killed run still writes a record
        record["rc"] = 124
        record["ok"] = False
        record["status"] = "timeout"
        tail = exc.stderr or exc.stdout or b""
        if isinstance(tail, bytes):
            tail = tail.decode(errors="replace")
        record["tail"] = tail[-2000:]
    record["elapsed_s"] = round(time.perf_counter() - t0, 1)
    return record


def main() -> int:
    parser = argparse.ArgumentParser("failover_run")
    parser.add_argument("--rows", type=int, default=100_000)
    parser.add_argument("--iters", type=int, default=10)
    parser.add_argument("--timeout", type=float, default=300.0)
    parser.add_argument("--out", default=None,
                        help="record path (default: next "
                             "bench_results/failover_rNN.json)")
    args = parser.parse_args()
    record = run(args.rows, args.iters, args.timeout)
    path = args.out or next_record_path()
    with open(path, "w", encoding="utf-8") as f:
        json.dump(record, f, indent=1)
        f.write("\n")
    print(json.dumps({"record": os.path.relpath(path, ROOT), **record}))
    return 0 if record["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
