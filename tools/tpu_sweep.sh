#!/bin/bash
# Full real-TPU sweep for the moment the axon relay answers (run from
# the repo root).  Results append to bench_results/tpu_round5.md and
# config 1 auto-refreshes bench_results/tpu_verified.json.  One TPU
# process at a time; each config is a fresh subprocess.
set -u
cd "$(dirname "$0")/.."
OUT=bench_results/tpu_round5.md
date=$(date -I)
echo "# Real-TPU measurements, round 5 ($date)" >> "$OUT"
echo >> "$OUT"
for cfg in "1 10000000 20" "2 2000000 10" "4 12000000 3" "5 2000000 5" "7 2000000 20"; do
  set -- $cfg
  echo "## config $1 (rows=$2)" >> "$OUT"
  echo '```json' >> "$OUT"
  BENCH_CONFIG=$1 BENCH_ROWS=$2 BENCH_ITERS=$3 timeout 3600 python bench.py \
    2>>"$OUT.log" | tail -1 >> "$OUT"
  echo '```' >> "$OUT"
  echo >> "$OUT"
done
echo "sweep done: $OUT"
