#!/usr/bin/env python
"""Multichip dryrun runner that ALWAYS records a result.

ROADMAP item 3 notes the MULTICHIP bench recording gap: the round-1
multichip run timed out (rc=124, MULTICHIP_r01) and left nothing but a
truncated log — a wedged run must still produce a structured record so
the history distinguishes "timed out" from "never ran".  This runner
executes `__graft_entry__.dryrun_multichip(N)` in a subprocess under a
hard timeout and writes `bench_results/multichip_rNN.json` (next free
index) with an explicit `status` of "ok" | "timeout" | "error" — on
EVERY outcome, including the process being killed.

Usage:
    python tools/multichip_run.py [--devices 8] [--timeout 600]
                                  [--out PATH]

`make multichip` wraps this with the tier-1 defaults.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def next_record_path() -> str:
    results = os.path.join(ROOT, "bench_results")
    os.makedirs(results, exist_ok=True)
    taken = set()
    for p in glob.glob(os.path.join(results, "multichip_r*.json")):
        m = re.search(r"multichip_r(\d+)\.json$", p)
        if m:
            taken.add(int(m.group(1)))
    n = 1
    while n in taken:
        n += 1
    return os.path.join(results, f"multichip_r{n:02d}.json")


def run(n_devices: int, timeout_s: float, mode: str = "dryrun",
        rows: int = 2_000_000) -> dict:
    if mode == "mesh":
        # the mesh-scan A/B: BENCH_CONFIG=22 (ISSUE 19) runs the
        # mesh-placed FUSED-DECODE scan (stored bytes to ranked
        # answer) vs the PR 15 mesh-over-host-windows leg vs the
        # single-chip control, with in-bench bit-identity across all
        # three legs, k-way-merge routing asserts, and the additive
        # top-k egress bound at two group cardinalities
        # (BENCH_CONFIG=19 remains the PR 15 two-leg A/B, selectable
        # via MESH_BENCH_CONFIG).  On this box the rung is the CPU
        # virtual mesh (--xla_force_host_platform_device_count); a TPU
        # host runs the identical command on real chips and the
        # record's backend/fallback labels say which it was
        cmd = [sys.executable, "bench.py"]
        env = dict(os.environ)
        env["BENCH_CONFIG"] = env.get("MESH_BENCH_CONFIG", "22")
        env.setdefault("BENCH_ROWS", str(rows))
        env["MESH_BENCH_DEVICES"] = str(n_devices)
        flags = env.get("XLA_FLAGS", "")
        want = f"--xla_force_host_platform_device_count={n_devices}"
        if "host_platform_device_count" not in flags:
            env["XLA_FLAGS"] = f"{flags} {want}".strip()
    else:
        cmd = [sys.executable, "-c",
               f"import __graft_entry__; "
               f"__graft_entry__.dryrun_multichip({n_devices}); "
               f"print('dryrun OK')"]
        env = None
    t0 = time.perf_counter()
    record = {"mode": mode, "n_devices": n_devices,
              "timeout_s": timeout_s, "cmd": " ".join(cmd)}
    try:
        proc = subprocess.run(cmd, cwd=ROOT, capture_output=True,
                              text=True, timeout=timeout_s, env=env)
        record["rc"] = proc.returncode
        record["ok"] = proc.returncode == 0
        # rc=124 is how an outer `timeout(1)` reports — classify it as
        # a timeout even when the wedge happened below us
        record["status"] = ("ok" if proc.returncode == 0 else
                            "timeout" if proc.returncode == 124 else
                            "error")
        record["tail"] = (proc.stderr or proc.stdout or "")[-2000:]
        if mode == "mesh" and proc.returncode == 0:
            # bench.py prints ONE result JSON on its last stdout line
            for line in reversed(proc.stdout.strip().splitlines()):
                try:
                    record["result"] = json.loads(line)
                    break
                except json.JSONDecodeError:
                    continue
    except subprocess.TimeoutExpired as exc:
        # THE recording-gap fix: a killed run still writes a record
        record["rc"] = 124
        record["ok"] = False
        record["status"] = "timeout"
        tail = exc.stderr or exc.stdout or b""
        if isinstance(tail, bytes):
            tail = tail.decode(errors="replace")
        record["tail"] = tail[-2000:]
    record["elapsed_s"] = round(time.perf_counter() - t0, 1)
    return record


def main() -> int:
    parser = argparse.ArgumentParser("multichip_run")
    parser.add_argument("--devices", type=int, default=8)
    parser.add_argument("--timeout", type=float, default=600.0)
    parser.add_argument("--mode", choices=("dryrun", "mesh"),
                        default="dryrun",
                        help="dryrun = the shard_map program dryrun; "
                             "mesh = the BENCH_CONFIG=19 mesh-scan "
                             "A/B with in-bench bit-identity checks")
    parser.add_argument("--rows", type=int, default=2_000_000)
    parser.add_argument("--out", default=None,
                        help="record path (default: next "
                             "bench_results/multichip_rNN.json)")
    args = parser.parse_args()
    record = run(args.devices, args.timeout, mode=args.mode,
                 rows=args.rows)
    path = args.out or next_record_path()
    with open(path, "w", encoding="utf-8") as f:
        json.dump(record, f, indent=1)
        f.write("\n")
    print(json.dumps({"record": os.path.relpath(path, ROOT), **record}))
    return 0 if record["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
