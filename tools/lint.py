#!/usr/bin/env python
"""Stdlib lint gate (the reference CI runs fmt+clippy -D warnings,
.github/workflows/ci.yml:52-72; this image has no ruff/flake8 and
installs are off-limits, so the gate is an AST checker with zero
dependencies).

Checks, all hard failures:
  - syntax errors (ast.parse)
  - unused imports (module scope and function scope; `__init__.py`
    re-export surfaces are exempt, as is anything in __all__ or marked
    `# noqa`)
  - trailing whitespace / tabs in indentation
  - mutable default arguments (def f(x=[]) / {} / set())
  - bare `except:` clauses
  - aiohttp session HTTP calls without an explicit `timeout=` anywhere
    under horaedb_tpu/ (docs/robustness.md: aiohttp's 5-minute default
    total timeout must never be inherited on the serving path)
  - WAL durability rules under horaedb_tpu/wal/: a module that writes
    file bytes must also os.fsync (an fsync-free WAL write is not an
    ack point), and bare `time.time()` is banned (replay must order by
    the persisted id clock; tests inject clocks)
  - tiered scan-cache discipline under horaedb_tpu/: direct
    `scan_cache.put/get` / `encoded_cache.put/get` calls are the
    reader's alone — writers insert through the tiered admission API
    (EncodedSegmentCache.admit), so cache-coherence reasoning lives in
    exactly one module (storage/encoded_cache.py's docstring)
  - rollup coverage discipline under horaedb_tpu/: scan-shaped calls
    on rollup tier tables outside horaedb_tpu/rollup/ are an error —
    reads go through the planner's coverage API
    (RollupManager.covers/try_serve), the one place that knows which
    segments' cells are current (docs/rollups.md)
  - metric registration hygiene under horaedb_tpu/: every
    `registry.counter/gauge/histogram(...)` call must pass non-empty
    help text (docs/observability.md — /metrics is an operator
    surface; a bare series name is not documentation)
  - pipeline/executor discipline under horaedb_tpu/storage/: CPU work
    dispatched off the event loop must go through `runtimes.run` (or
    asyncio.to_thread, which also copies contextvars) — bare
    `loop.run_in_executor(...)`, `ThreadPoolExecutor(...)` and
    `<pool>.submit(...)` do NOT propagate contextvars, so a scan
    pipeline stage dispatched that way silently drops its trace/
    deadline attribution (docs/observability.md, pipeline section)
  - loop-registry discipline under horaedb_tpu/: spawning a
    long-running loop coroutine (a callee whose name contains "loop")
    via bare `asyncio.create_task` / `loop.create_task` /
    `ensure_future` is an error outside common/loops.py — loops go
    through `loops.spawn(...)` so every one is registered, heartbeats,
    and appears in GET /debug/tasks (a loop born unwatched is a loop
    that hangs unseen; docs/observability.md, background plane)
  - EncodedSegment decode discipline under horaedb_tpu/: host-decoding
    a sidecar's encoded buffers (deserialize / assemble / concat /
    decode_column ...) outside storage/sidecar.py, ops/ and the
    reader's dispatch seam is an error — decode goes through the
    reader so the fused device dispatch (ops/device_decode.py) can
    serve eligible plans instead of silently re-growing host decode
  - scanagent HTTP discipline under horaedb_tpu/scanagent/: every
    http-ish client call (session/client/http receivers) must carry an
    explicit timeout= (the PR-2 session rule, extended — a near-data
    RPC without a bound reintroduces the 5-minute default on the
    query path), and raw `store.get/get_range/get_stream` on the
    COORDINATOR side (outside agent.py) is an error — covered-segment
    fallbacks go through the reader's local pump, the one declared
    fallback seam
  - memory-ledger budget discipline under horaedb_tpu/: every byte
    budget a config dataclass exposes (a field named `*_bytes`) must
    correspond to a memory-ledger account registered at open
    (common/memledger.py) — mapped in _BUDGET_FIELD_ACCOUNTS to the
    account kind its owner registers, or listed in
    _BUDGET_FIELD_EXEMPT with the reason it holds no resident bytes.
    A budget nobody ledgers is RSS nobody can attribute, which is how
    the 1B-row ladder's "169 GiB projected" stays hand math
    (docs/observability.md, memory plane)
  - replication fencing discipline under horaedb_tpu/wal/ and
    horaedb_tpu/cluster/: a manifest/SST commit call
    (write_stamped / _persist_stamped / manifest.add_file) whose
    enclosing function never references a fence is an error — on the
    replicated path every commit revalidates the lease epoch first
    (cluster/replication.py Lease.check), or a primary that lost its
    lease mid-flush can still publish files the NEW primary's replay
    doesn't know about (docs/robustness.md, split-brain domain)
  - combine grid discipline under horaedb_tpu/: allocating a dense
    `(groups, num_buckets)`-shaped array (np.zeros/full/empty/ones
    with a 2-tuple shape whose second element is named like a bucket
    count) outside storage/combine.py is an error — the output-grid
    cliff the sparse combine killed (bench_results/scale_r5.md) grows
    back one "just this once" grid at a time; aggregation output goes
    through the combine API (combine_parts / combine_top_k /
    merge_downsample_results)

Usage: python tools/lint.py [paths...]   (default: horaedb_tpu tests
bench.py __graft_entry__.py)
"""

from __future__ import annotations

import ast
import pathlib
import sys
from typing import Optional

DEFAULT_PATHS = ["horaedb_tpu", "tests", "bench.py", "__graft_entry__.py"]


def iter_files(paths: list[str]):
    for p in paths:
        path = pathlib.Path(p)
        if path.is_dir():
            yield from sorted(path.rglob("*.py"))
        elif path.suffix == ".py":
            yield path


class _Names(ast.NodeVisitor):
    """Collect every name read anywhere in the tree (conservative:
    attribute roots and string annotations count)."""

    def __init__(self) -> None:
        self.used: set[str] = set()

    def visit_Name(self, node: ast.Name) -> None:
        self.used.add(node.id)
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        root = node
        while isinstance(root, ast.Attribute):
            root = root.value
        if isinstance(root, ast.Name):
            self.used.add(root.id)
        self.generic_visit(node)

    def visit_Constant(self, node: ast.Constant) -> None:
        # string annotations / forward refs / docstrings may reference
        # imported names textually — count identifier-looking tokens
        if isinstance(node.value, str) and len(node.value) < 4096:
            for tok in (node.value.replace(".", " ").replace("[", " ")
                        .replace("]", " ").split()):
                if tok.isidentifier():
                    self.used.add(tok)


# HTTP-verb methods on a client session object; any such call under
# horaedb_tpu/ must carry an explicit timeout= keyword
_SESSION_HTTP_VERBS = {"get", "post", "put", "delete", "head", "options",
                       "patch", "request"}


def _session_call_without_timeout(node: ast.Call) -> bool:
    """True for `<...session...>.<verb>(...)` calls missing timeout=.
    The receiver chain is matched on the token "session" (session,
    self._session, cls.session, ...) — conservative enough to skip
    aiohttp server/request objects and pyarrow readers."""
    func = node.func
    if not isinstance(func, ast.Attribute) \
            or func.attr not in _SESSION_HTTP_VERBS:
        return False
    chain = []
    cur = func.value
    while isinstance(cur, ast.Attribute):
        chain.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        chain.append(cur.id)
    if not any("session" in part.lower() for part in chain):
        return False
    return not any(kw.arg == "timeout" for kw in node.keywords)


# modules that OWN the scan-cache tiers: the reader (lookup + read-path
# population) and the tier implementations themselves.  Everyone else
# goes through the tiered API (admit/invalidate/clear/stats/
# mark_missing) — direct put/get elsewhere bypasses the admission
# discipline and the byte accounting
_CACHE_OWNERS = {"read.py", "scan_cache.py", "encoded_cache.py"}
_CACHE_TOKENS = ("scan_cache", "encoded_cache")


def _tiered_cache_violation(node: ast.Call) -> bool:
    """True for `<...scan_cache|encoded_cache...>.put/get(...)` calls —
    the lookup/population surface only the reader may touch."""
    func = node.func
    if not isinstance(func, ast.Attribute) or func.attr not in ("put",
                                                                "get"):
        return False
    chain = []
    cur = func.value
    while isinstance(cur, ast.Attribute):
        chain.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        chain.append(cur.id)
    return any(tok in part for part in chain for tok in _CACHE_TOKENS)


# rollup tier tables are read ONLY through the planner's coverage API
# (rollup/manager.py: covers/try_serve): a direct scan of a rollup
# table elsewhere bypasses the dirty/rolling/memtable coverage checks
# and can serve stale pre-aggregates (docs/rollups.md).  Writes/admin
# (compact/scrub) stay allowed; the scan-shaped surface does not.
_ROLLUP_SCAN_METHODS = {"scan", "scan_segments", "scan_aggregate",
                        "plan_query", "execute_plan", "build_scan_plan"}
_ROLLUP_TOKENS = ("rollup", "tier")


def _receiver_chain(func: ast.Attribute) -> list[str]:
    """Attribute/Name/Subscript tokens of a call receiver, e.g.
    `self.rollups.tiers[ms].scan(...)` -> [tiers, rollups, self]."""
    chain = []
    cur = func.value
    while True:
        if isinstance(cur, ast.Attribute):
            chain.append(cur.attr)
            cur = cur.value
        elif isinstance(cur, ast.Subscript):
            cur = cur.value
        elif isinstance(cur, ast.Call):
            cur = cur.func
        else:
            break
    if isinstance(cur, ast.Name):
        chain.append(cur.id)
    return chain


def _rollup_scan_violation(node: ast.Call) -> bool:
    """True for `<...rollup|tier...>.scan/plan_query/... (...)` calls —
    rollup-tier reads outside the coverage API."""
    func = node.func
    if not isinstance(func, ast.Attribute) \
            or func.attr not in _ROLLUP_SCAN_METHODS:
        return False
    return any(tok in part.lower() for part in _receiver_chain(func)
               for tok in _ROLLUP_TOKENS)


# executor-dispatch surfaces that DON'T copy contextvars: pipeline
# stage work under horaedb_tpu/storage/ dispatched through these loses
# the ambient trace and deadline (stage attribution silently drops).
# runtimes.run copies the context explicitly and asyncio.to_thread
# copies it by contract — those are the sanctioned dispatches.
_EXECUTOR_CTORS = {"ThreadPoolExecutor", "ProcessPoolExecutor"}


def _bare_executor_dispatch(node: ast.Call) -> Optional[str]:
    """Reason string for `loop.run_in_executor(...)` /
    `ThreadPoolExecutor(...)` / `<pool|executor>.submit(...)` calls —
    context-dropping dispatch paths; None when the call is fine."""
    func = node.func
    if isinstance(func, ast.Attribute) and func.attr == "run_in_executor":
        return "run_in_executor"
    if isinstance(func, ast.Attribute) and func.attr == "submit":
        if any("pool" in part.lower() or "executor" in part.lower()
               for part in _receiver_chain(func)):
            return "executor .submit"
    name = None
    if isinstance(func, ast.Name):
        name = func.id
    elif isinstance(func, ast.Attribute):
        name = func.attr
    if name in _EXECUTOR_CTORS:
        return f"{name} construction"
    return None


# task-spawn surfaces; spawning a LOOP through any of these bypasses
# the loop registry (no heartbeat, no watchdog, invisible to
# /debug/tasks).  The discriminator is the ARGUMENT: a call to a
# function whose name contains "loop" — the repo's background loops
# are all named *_loop / _loop by convention, and the spawn helper
# (common/loops.py, the one exempt module) keeps that convention
# enforceable.
_TASK_SPAWNERS = {"create_task", "ensure_future"}


def _unwatched_loop_spawn(node: ast.Call) -> bool:
    """True for `asyncio.create_task(self._x_loop(...))`-shaped calls —
    a long-running loop spawned outside the loop registry."""
    func = node.func
    if not isinstance(func, ast.Attribute) \
            or func.attr not in _TASK_SPAWNERS:
        return False
    if not node.args:
        return False
    arg = node.args[0]
    if not isinstance(arg, ast.Call):
        return False
    f = arg.func
    if isinstance(f, ast.Attribute):
        callee = f.attr
    elif isinstance(f, ast.Name):
        callee = f.id
    else:
        return False
    return "loop" in callee.lower()


# EncodedSegment decode discipline: the sidecar's encoded buffers are
# host-decoded ONLY inside the dispatch seam — storage/sidecar.py (the
# format), ops/ (the encode/decode primitives and the fused device
# dispatch), storage/read.py (the reader's routing) and
# storage/compaction.py (the write-side merge that builds sidecars).
# A new call site elsewhere silently reintroduces host decode behind
# the device-native path's back (ISSUE 12 / ROADMAP item 2): decode
# goes through the reader, which knows whether the fused device
# dispatch should serve the plan instead.
_DECODE_SEAM_FILES = {"sidecar.py", "read.py", "compaction.py"}
_DECODE_ENTRY_POINTS = {"deserialize", "assemble_parts",
                        "assemble_segment", "concat_encoded",
                        "merge_parts", "load_sst_encoded",
                        "decode_column", "decode_to_arrow",
                        "apply_leaves_host"}
# names distinctive enough to flag even as bare calls (a bare
# `deserialize(...)` could be anything; these cannot)
_DECODE_DISTINCT = _DECODE_ENTRY_POINTS - {"deserialize", "merge_parts"}
_DECODE_RECEIVER_TOKENS = ("sidecar", "encode")


def _host_decode_outside_seam(node: ast.Call) -> bool:
    """True for `sidecar.deserialize(...)` / `encode.decode_column(...)`
    / bare `assemble_parts(...)`-shaped calls — EncodedSegment decode
    primitives invoked outside the dispatch seam."""
    func = node.func
    if isinstance(func, ast.Attribute):
        if func.attr not in _DECODE_ENTRY_POINTS:
            return False
        return any(tok in part.lower()
                   for part in _receiver_chain(func)
                   for tok in _DECODE_RECEIVER_TOKENS)
    if isinstance(func, ast.Name):
        return func.id in _DECODE_DISTINCT
    return False


# scanagent HTTP discipline (extends the PR-2 session rule): under
# horaedb_tpu/scanagent/ EVERY http-ish client call (receiver token
# session/client/http, not just "session") must carry an explicit
# timeout= — the agent protocol's whole point is bounded near-data
# RPCs that honor the propagated deadline; one bare call reintroduces
# aiohttp's 5-minute default on the query path
_SCANAGENT_HTTP_TOKENS = ("session", "client", "http")


def _scanagent_http_without_timeout(node: ast.Call) -> bool:
    func = node.func
    if not isinstance(func, ast.Attribute) \
            or func.attr not in _SESSION_HTTP_VERBS:
        return False
    if not any(tok in part.lower() for part in _receiver_chain(func)
               for tok in _SCANAGENT_HTTP_TOKENS):
        return False
    return not any(kw.arg == "timeout" for kw in node.keywords)


# scanagent raw-read discipline: the COORDINATOR side of the near-data
# plane never reads segment objects itself — covered segments are
# served by agents, and failures fall back through the reader's local
# pump (storage/read.py, the one declared fallback seam with streamed
# reads, byte accounting, and tenant charging).  A raw `store.get(...)`
# in scanagent/ outside agent.py (the near-data side, whose job IS
# reading its shard) silently re-grows coordinator read amplification
# behind the routing's back.
_STORE_READ_METHODS = {"get", "get_range", "get_stream"}


def _scanagent_raw_store_read(node: ast.Call) -> bool:
    func = node.func
    if not isinstance(func, ast.Attribute) \
            or func.attr not in _STORE_READ_METHODS:
        return False
    return any("store" in part.lower()
               for part in _receiver_chain(func))


# metric-factory methods on a registry object; any such call under
# horaedb_tpu/ must pass non-empty help text (positional or help_=)
_METRIC_FACTORIES = {"counter", "gauge", "histogram"}


_MESH_CONSTRUCTORS = {"Mesh", "shard_map", "NamedSharding"}


def _lax_sort_outside_merge(node: ast.Call) -> bool:
    """`jax.lax.sort` call sites outside ops/merge.py: the engine's
    variadic lexicographic sort has ONE seam (ops/merge.lex_sort) and
    one presorted-run bypass (kway_merge_perm) — a stray lax.sort is
    how the O(n log n) full sort quietly grows back into a path the
    k-way merge already made sort-free.  Matches `lax.sort(...)` and
    `jax.lax.sort(...)` receivers (sort_key_val etc. included via the
    attr prefix check)."""
    func = node.func
    if not isinstance(func, ast.Attribute) \
            or not func.attr.startswith("sort"):
        return False
    chain = []
    cur = func.value
    while isinstance(cur, ast.Attribute):
        chain.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        chain.append(cur.id)
    return "lax" in chain


def _bare_jax_jit(node: ast.Attribute) -> bool:
    """Any `jax.jit` reference outside common/deviceprof.py: the
    compile ledger only sees seams that route through deviceprof.jit —
    a bare jax.jit (decorator, functools.partial, or direct call; all
    three forms contain the `jax.jit` attribute node this matches)
    compiles invisibly, so its recompile storms, dispatch wall, and
    compile seconds never reach /debug/device or the per-trace
    attribution.  Wrap with deviceprof.jit, or noqa WITH a reason (the
    bench suite's unprofiled baselines are the intended escape)."""
    return (node.attr == "jit" and isinstance(node.value, ast.Name)
            and node.value.id == "jax")


def _mesh_construction_outside_parallel(node: ast.Call) -> bool:
    """Mesh/shard_map/NamedSharding construction outside
    horaedb_tpu/parallel/: mesh topology and sharding specs stay
    declared in ONE place (parallel/mesh.py builds meshes,
    parallel/scan.py owns the shard_map programs and placement
    helpers) — a second construction site is how two halves of the
    engine end up disagreeing about axis names and layouts."""
    func = node.func
    name = None
    if isinstance(func, ast.Name):
        name = func.id
    elif isinstance(func, ast.Attribute):
        name = func.attr
    return name in _MESH_CONSTRUCTORS


def _metric_call_without_help(node: ast.Call) -> bool:
    """True for `<...registry...>.counter/gauge/histogram(...)` calls
    whose help text is missing or an empty string literal.  Receivers
    are matched on the token "registry"/"metrics" (registry,
    self.registry, metrics, ...) so unrelated .counter() methods on
    other objects don't trip the rule."""
    func = node.func
    if not isinstance(func, ast.Attribute) \
            or func.attr not in _METRIC_FACTORIES:
        return False
    chain = []
    cur = func.value
    while isinstance(cur, ast.Attribute):
        chain.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        chain.append(cur.id)
    if not any("registry" in part.lower() or part.lower() == "metrics"
               for part in chain):
        return False
    help_arg = None
    if len(node.args) >= 2:
        help_arg = node.args[1]
    else:
        for kw in node.keywords:
            if kw.arg == "help_":
                help_arg = kw.value
    if help_arg is None:
        return True
    return isinstance(help_arg, ast.Constant) and help_arg.value == ""


# numpy/jax array constructors that take a shape first argument; a
# 2-tuple shape whose SECOND element is named like a bucket count is
# the dense output-grid idiom the sparse combine replaced
_GRID_ALLOCATORS = {"zeros", "full", "empty", "ones"}


def _dense_grid_allocation(node: ast.Call) -> bool:
    """True for `np.zeros((g, num_buckets))`-shaped calls — a dense
    (groups, buckets) output grid allocated directly.  The bucket axis
    is recognized by name ("bucket" in the second shape element's
    identifier), so per-window partials and unrelated 2-D arrays don't
    trip the rule."""
    func = node.func
    if not (isinstance(func, ast.Attribute)
            and func.attr in _GRID_ALLOCATORS
            and isinstance(func.value, ast.Name)
            and func.value.id in ("np", "numpy", "jnp")):
        return False
    if not node.args:
        return False
    shape = node.args[0]
    if not (isinstance(shape, ast.Tuple) and len(shape.elts) == 2):
        return False
    second = shape.elts[1]
    if isinstance(second, ast.Name):
        name = second.id
    elif isinstance(second, ast.Attribute):
        name = second.attr
    else:
        return False
    return "bucket" in name.lower()


def lint_file(path: pathlib.Path) -> list[str]:
    problems: list[str] = []
    text = path.read_text()
    lines = text.splitlines()
    for i, line in enumerate(lines, 1):
        if line != line.rstrip():
            problems.append(f"{path}:{i}: trailing whitespace")
        stripped_len = len(line) - len(line.lstrip(" \t"))
        if "\t" in line[:stripped_len]:
            problems.append(f"{path}:{i}: tab in indentation")
    try:
        tree = ast.parse(text, filename=str(path))
    except SyntaxError as e:
        problems.append(f"{path}:{e.lineno}: syntax error: {e.msg}")
        return problems

    names = _Names()
    names.visit(tree)
    exported: set[str] = set()
    for node in tree.body:
        if (isinstance(node, ast.Assign)
                and any(isinstance(t, ast.Name) and t.id == "__all__"
                        for t in node.targets)
                and isinstance(node.value, (ast.List, ast.Tuple))):
            exported |= {e.value for e in node.value.elts
                         if isinstance(e, ast.Constant)}

    is_init = path.name == "__init__.py"
    for node in ast.walk(tree):
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            if is_init:
                continue  # re-export surface
            if (isinstance(node, ast.ImportFrom)
                    and node.module == "__future__"):
                continue
            src = lines[node.lineno - 1] if node.lineno <= len(lines) else ""
            if "noqa" in src:
                continue
            for alias in node.names:
                if alias.name == "*":
                    continue
                bound = (alias.asname or alias.name).split(".")[0]
                if bound not in names.used and bound not in exported:
                    problems.append(
                        f"{path}:{node.lineno}: unused import {bound!r}")
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for d in node.args.defaults + node.args.kw_defaults:
                if isinstance(d, (ast.List, ast.Dict, ast.Set)):
                    problems.append(
                        f"{path}:{node.lineno}: mutable default argument "
                        f"in {node.name}()")
        elif isinstance(node, ast.ExceptHandler) and node.type is None:
            problems.append(f"{path}:{node.lineno}: bare except")
        elif (isinstance(node, ast.Call) and "scanagent" in path.parts
                and "horaedb_tpu" in path.parts
                and _scanagent_http_without_timeout(node)):
            src = lines[node.lineno - 1] if node.lineno <= len(lines) else ""
            if "noqa" not in src:
                problems.append(
                    f"{path}:{node.lineno}: scanagent HTTP call without "
                    "an explicit timeout= — agent RPCs must be bounded "
                    "by min([scanagent] timeout, deadline remaining) "
                    "and carry X-Deadline-Ms (docs/robustness.md)")
        elif (isinstance(node, ast.Call) and "scanagent" in path.parts
                and "horaedb_tpu" in path.parts
                and path.name != "agent.py"
                and _scanagent_raw_store_read(node)):
            src = lines[node.lineno - 1] if node.lineno <= len(lines) else ""
            if "noqa" not in src:
                problems.append(
                    f"{path}:{node.lineno}: raw store read on the "
                    "scanagent coordinator side — covered segments are "
                    "agent-served; failures fall back through the "
                    "reader's local pump (storage/read.py), the one "
                    "declared fallback seam")
        elif (isinstance(node, ast.Call) and "horaedb_tpu" in path.parts
                and _session_call_without_timeout(node)):
            src = lines[node.lineno - 1] if node.lineno <= len(lines) else ""
            if "noqa" not in src:
                problems.append(
                    f"{path}:{node.lineno}: aiohttp session call without "
                    "an explicit timeout= (would inherit the 5-minute "
                    "default; derive one from the deadline)")
        elif (isinstance(node, ast.Call) and "horaedb_tpu" in path.parts
                and path.name not in _CACHE_OWNERS
                and _tiered_cache_violation(node)):
            src = lines[node.lineno - 1] if node.lineno <= len(lines) else ""
            if "noqa" not in src:
                problems.append(
                    f"{path}:{node.lineno}: direct scan-cache put/get "
                    "outside the reader — writers go through the tiered "
                    "admission API (EncodedSegmentCache.admit); see "
                    "storage/encoded_cache.py")
        elif (isinstance(node, ast.Call) and "horaedb_tpu" in path.parts
                and "rollup" not in path.parts
                and _rollup_scan_violation(node)):
            src = lines[node.lineno - 1] if node.lineno <= len(lines) else ""
            if "noqa" not in src:
                problems.append(
                    f"{path}:{node.lineno}: direct rollup-tier scan "
                    "outside horaedb_tpu/rollup/ — reads go through the "
                    "planner's coverage API (RollupManager.covers/"
                    "try_serve), which is what keeps stale cells from "
                    "serving (docs/rollups.md)")
        elif (isinstance(node, ast.Call) and "horaedb_tpu" in path.parts
                and "storage" in path.parts
                and _bare_executor_dispatch(node) is not None):
            src = lines[node.lineno - 1] if node.lineno <= len(lines) else ""
            if "noqa" not in src:
                problems.append(
                    f"{path}:{node.lineno}: "
                    f"{_bare_executor_dispatch(node)} under "
                    "horaedb_tpu/storage/ — off-loop work goes through "
                    "runtimes.run (contextvar propagation), or a scan "
                    "pipeline stage silently drops its trace/deadline "
                    "attribution")
        elif (isinstance(node, ast.Call) and "horaedb_tpu" in path.parts
                and path.name != "loops.py"
                and _unwatched_loop_spawn(node)):
            src = lines[node.lineno - 1] if node.lineno <= len(lines) else ""
            if "noqa" not in src:
                problems.append(
                    f"{path}:{node.lineno}: long-running loop spawned "
                    "with bare create_task/ensure_future — use "
                    "common.loops.spawn(...) so the loop is registered, "
                    "heartbeats, and the watchdog can flag a stall "
                    "(GET /debug/tasks)")
        elif (isinstance(node, ast.Call) and "horaedb_tpu" in path.parts
                and not (path.name == "combine.py"
                         and "storage" in path.parts)
                and _dense_grid_allocation(node)):
            src = lines[node.lineno - 1] if node.lineno <= len(lines) else ""
            if "noqa" not in src:
                problems.append(
                    f"{path}:{node.lineno}: dense (groups, num_buckets) "
                    "grid allocated outside storage/combine.py — the "
                    "output-grid cliff grows back one grid at a time; "
                    "go through the combine API (combine_parts / "
                    "combine_top_k / merge_downsample_results)")
        elif (isinstance(node, ast.Call) and "horaedb_tpu" in path.parts
                and "ops" not in path.parts
                and path.name not in _DECODE_SEAM_FILES
                and _host_decode_outside_seam(node)):
            src = lines[node.lineno - 1] if node.lineno <= len(lines) else ""
            if "noqa" not in src:
                problems.append(
                    f"{path}:{node.lineno}: EncodedSegment encoded "
                    "buffers host-decoded outside the dispatch seam "
                    "(storage/sidecar.py, ops/, the reader) — new call "
                    "sites silently reintroduce the host decode the "
                    "device-native path removed; route reads through "
                    "the reader (ops/device_decode.py)")
        elif (isinstance(node, ast.Call) and "horaedb_tpu" in path.parts
                and path.name != "merge.py"
                and _lax_sort_outside_merge(node)):
            src = lines[node.lineno - 1] if node.lineno <= len(lines) else ""
            if "noqa" not in src:
                problems.append(
                    f"{path}:{node.lineno}: jax.lax.sort called "
                    "outside ops/merge.py — the device sort has one "
                    "seam (ops/merge.lex_sort) so presorted and k-way "
                    "-mergeable inputs can bypass it; call lex_sort / "
                    "kway_merge_perm instead (docs/parallel.md)")
        elif (isinstance(node, ast.Attribute)
                and "horaedb_tpu" in path.parts
                and path.name != "deviceprof.py"
                and _bare_jax_jit(node)):
            src = lines[node.lineno - 1] if node.lineno <= len(lines) else ""
            if "noqa" not in src:
                problems.append(
                    f"{path}:{node.lineno}: bare jax.jit outside "
                    "common/deviceprof.py — jitted seams route through "
                    "deviceprof.jit so the compile ledger, dispatch "
                    "profiler, and recompile-storm watchdog see them "
                    "(GET /debug/device; docs/observability.md); noqa "
                    "with a reason for intentional unprofiled sites")
        elif (isinstance(node, ast.Call) and "horaedb_tpu" in path.parts
                and "parallel" not in path.parts
                and _mesh_construction_outside_parallel(node)):
            src = lines[node.lineno - 1] if node.lineno <= len(lines) else ""
            if "noqa" not in src:
                problems.append(
                    f"{path}:{node.lineno}: Mesh/shard_map/"
                    "NamedSharding constructed outside "
                    "horaedb_tpu/parallel/ — mesh topology stays "
                    "declared in one place; build meshes via "
                    "parallel.mesh and place arrays via "
                    "parallel.scan's helpers (docs/parallel.md)")
        elif (isinstance(node, ast.Call) and "horaedb_tpu" in path.parts
                and _metric_call_without_help(node)):
            src = lines[node.lineno - 1] if node.lineno <= len(lines) else ""
            if "noqa" not in src:
                problems.append(
                    f"{path}:{node.lineno}: registry metric registered "
                    "with empty help text — /metrics is an operator "
                    "surface; describe the series "
                    "(docs/observability.md)")
        elif (isinstance(node, ast.Call) and "horaedb_tpu" in path.parts
                and path.name not in _PROMOTE_OWNERS
                and _promote_call(node)):
            src = lines[node.lineno - 1] if node.lineno <= len(lines) else ""
            if "noqa" not in src:
                problems.append(
                    f"{path}:{node.lineno}: promote() called outside "
                    "its declared owners — election ownership is the "
                    "StandbyMonitor (cluster/replication.py) and "
                    "PlacementController.promote_region "
                    "(cluster/placement.py); everything else (tests "
                    "aside) must go through them so exactly one code "
                    "path can take a region's lease")
    if "wal" in path.parts and "horaedb_tpu" in path.parts:
        problems.extend(_lint_wal_module(path, tree, lines))
    if ("horaedb_tpu" in path.parts
            and ("wal" in path.parts or "cluster" in path.parts)):
        problems.extend(_lint_fencing(path, tree, lines))
    if ("horaedb_tpu" in path.parts and "server" in path.parts
            and path.name == "main.py"):
        problems.extend(_lint_server_routes(path, tree, lines))
    return problems


def _is_call_to(node: ast.Call, mod: str, attr: str) -> bool:
    return (isinstance(node.func, ast.Attribute)
            and node.func.attr == attr
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id == mod)


# promote() call sites allowed under horaedb_tpu/: the module defining
# it (whose StandbyMonitor is THE election path) and the placement
# controller's promotion seam.  tests/ and tools/ are outside the
# horaedb_tpu package and unaffected.
_PROMOTE_OWNERS = {"replication.py", "placement.py"}


def _promote_call(node: ast.Call) -> bool:
    """A call spelled `promote(...)` or `<obj>.promote(...)` — the
    lease-acquiring failover entry point (cluster/replication.py)."""
    func = node.func
    if isinstance(func, ast.Name):
        return func.id == "promote"
    if isinstance(func, ast.Attribute):
        return func.attr == "promote"
    return False


def _lint_wal_module(path: pathlib.Path, tree: ast.AST,
                     lines: list[str]) -> list[str]:
    """WAL durability rules (docs/robustness.md, write durability):
    a wal/ module performing file `.write()` calls must fsync (an
    unfsynced WAL append is not an ack point), and bare `time.time()`
    never appears — flush aging and replay use injected clocks / the
    persisted monotonic id clock so torture schedules are
    deterministic."""
    problems: list[str] = []
    has_fsync = False
    write_calls: list[int] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        src = (lines[node.lineno - 1]
               if node.lineno <= len(lines) else "")
        if _is_call_to(node, "os", "fsync"):
            has_fsync = True
        elif (isinstance(node.func, ast.Attribute)
                and node.func.attr == "write"
                and not (isinstance(node.func.value, ast.Attribute)
                         or "noqa" in src)):
            # direct `<name>.write(...)` — the file-handle shape; method
            # chains (self.inner.write, sink.stream.write) are storage
            # or arrow surfaces with their own disciplines
            write_calls.append(node.lineno)
        elif _is_call_to(node, "time", "time") and "noqa" not in src:
            problems.append(
                f"{path}:{node.lineno}: bare time.time() in wal/ "
                "(inject a clock; replay must use the persisted id "
                "clock)")
    if write_calls and not has_fsync:
        problems.append(
            f"{path}:{write_calls[0]}: file write in wal/ with no "
            "os.fsync anywhere in the module — an unfsynced WAL write "
            "must never be an ack point")
    return problems


# manifest/SST commit surface on the replicated path: any of these
# called under horaedb_tpu/wal/ or horaedb_tpu/cluster/ publishes
# files other nodes will read, so the enclosing function must
# revalidate the lease epoch (reference something fence-named) before
# committing — a stale-epoch primary must never commit
_FENCED_COMMIT_METHODS = {"write_stamped", "_persist_stamped", "add_file"}


def _lint_fencing(path: pathlib.Path, tree: ast.AST,
                  lines: list[str]) -> list[str]:
    """Replication fencing discipline (docs/robustness.md, split-brain
    domain): under wal/ and cluster/, a function that calls a
    manifest/SST commit method without referencing a fence anywhere in
    its body is a commit site a stale-epoch primary could still reach
    after losing its lease.  The fence seam is duck-typed
    (IngestStorage.fence -> Lease.check), so 'references a fence' is
    the name-level contract the AST can see."""
    problems: list[str] = []
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        commit_calls: list[int] = []
        has_fence_ref = False
        for node in ast.walk(fn):
            if isinstance(node, ast.Name) and "fence" in node.id.lower():
                has_fence_ref = True
            elif (isinstance(node, ast.Attribute)
                    and "fence" in node.attr.lower()):
                has_fence_ref = True
            elif (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _FENCED_COMMIT_METHODS):
                commit_calls.append(node.lineno)
        if has_fence_ref or not commit_calls:
            continue
        for lineno in commit_calls:
            src = lines[lineno - 1] if lineno <= len(lines) else ""
            if "noqa" in src:
                continue
            problems.append(
                f"{path}:{lineno}: unfenced manifest/SST commit in "
                f"{fn.name}() under the replicated path — revalidate "
                "the lease epoch first (await self.fence.check(); "
                "cluster/replication.py), or a primary that lost its "
                "lease mid-flush can still publish files")
    return problems


# every HTTP route in server/main.py must be declared in one of these
# endpoint sets: the admission+tenant middleware chain dispatches on
# them, so a handler registered outside them silently bypasses
# isolation (no tenant scope, no admission, no deadline default) —
# exactly the hole a "quick internal endpoint" opens under overload
_ENDPOINT_SETS = ("_QUERY_ENDPOINTS", "_WRITE_ENDPOINTS",
                  "_UNGOVERNED_ENDPOINTS")
_ROUTE_VERBS = {"get", "post", "put", "delete", "head", "patch", "route"}


def _frozenset_literal(node: ast.AST) -> Optional[set]:
    """The string members of a `frozenset({...})` / `frozenset([...])`
    assignment value, or None when it isn't one."""
    if not (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
            and node.func.id == "frozenset" and node.args):
        return None
    arg = node.args[0]
    if not isinstance(arg, (ast.Set, ast.List, ast.Tuple)):
        return None
    out = set()
    for e in arg.elts:
        if not (isinstance(e, ast.Constant) and isinstance(e.value, str)):
            return None
        out.add(e.value)
    return out


def _lint_server_routes(path: pathlib.Path, tree: ast.AST,
                        lines: list[str]) -> list[str]:
    """Middleware-chain coverage for the HTTP server: collect the
    module's endpoint frozensets and every `@routes.<verb>("<path>")`
    decorator; a registered path missing from all three sets is an
    error (docs/robustness.md, tenant isolation failure domains)."""
    problems: list[str] = []
    declared: set = set()
    found_sets = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id in _ENDPOINT_SETS:
                    members = _frozenset_literal(node.value)
                    if members is not None:
                        declared |= members
                        found_sets.add(t.id)
    missing_sets = set(_ENDPOINT_SETS) - found_sets
    if missing_sets:
        problems.append(
            f"{path}:1: endpoint set(s) {sorted(missing_sets)} missing "
            "or not frozenset literals — the admission+tenant "
            "middleware chain dispatches on them")
        return problems
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for dec in node.decorator_list:
            if not (isinstance(dec, ast.Call)
                    and isinstance(dec.func, ast.Attribute)
                    and dec.func.attr in _ROUTE_VERBS
                    and isinstance(dec.func.value, ast.Name)
                    and dec.func.value.id == "routes"
                    and dec.args
                    and isinstance(dec.args[0], ast.Constant)
                    and isinstance(dec.args[0].value, str)):
                continue
            route = dec.args[0].value
            src = (lines[dec.lineno - 1]
                   if dec.lineno <= len(lines) else "")
            if route not in declared and "noqa" not in src:
                problems.append(
                    f"{path}:{dec.lineno}: route {route!r} registered "
                    "outside the admission+tenant middleware chain — "
                    "add it to _QUERY_ENDPOINTS / _WRITE_ENDPOINTS "
                    "(governed) or _UNGOVERNED_ENDPOINTS (explicitly "
                    "exempt ops/admin surface)")
    return problems


# ---- memory-ledger budget discipline (cross-file) -------------------------
# Config byte-budget field -> the ledger account kind its owning
# component registers at open.  New `*_bytes` config fields must be
# added here (and their owner must register the account) or to the
# exempt set below with the reason they hold no resident bytes.
_BUDGET_FIELD_ACCOUNTS = {
    "cache_max_bytes": "scan_cache",        # HBM windows + stacks (read.py)
    "tier2_max_bytes": "encoded_cache",     # host-RAM encoded parts
    "memo_max_bytes": "parts_memo",         # aggregate-partial memo
    "inflight_bytes": "pipeline_inflight",  # pipeline in-flight budget
    "flush_bytes": "memtable",              # memtable flush threshold
}
_BUDGET_FIELD_EXEMPT = {
    # [scan.decode] per-dispatch upload admission gate: the upload
    # lives on DEVICE for one dispatch (memory_device_bytes covers it)
    "max_upload_bytes",
    # [scan.mesh] per-round transient-grid admission gate: the partial
    # grid lives on DEVICE for one round dispatch
    # (memory_device_bytes covers it), nothing host-resident
    "max_grid_bytes",
    # [scanagent] response-size refusal cap: an agent never buffers
    # past it, and the coordinator's received partials are charged to
    # the scanagent_wire flow account
    "max_partial_bytes",
    # [tenants] token-bucket burst capacities: RATE limits (bytes per
    # second), not resident bytes
    "scan_burst_bytes", "wal_burst_bytes",
    # [scan] whole-segment-vs-streamed routing threshold; the streamed
    # bytes themselves are charged to the streamed_mmap flow account
    "stream_read_min_bytes",
    # [wal] segment ROTATION size and group-commit coalescing bound:
    # sizing knobs for on-disk files / a transient commit queue — the
    # resident WAL bytes are the wal_backlog account
    "segment_bytes", "max_group_bytes",
    # [replication] per-read-RPC byte cap for WAL tail shipping: a
    # transient wire chunk (one aiohttp response body), appended to the
    # mirror file and dropped — nothing host-resident to ledger
    "max_batch_bytes",
    # ops.encode.DeviceBatch per-window memo state counter, not a
    # config budget: charged inside the scan_cache account's
    # windows_nbytes memo allowance
    "memo_bytes",
}


def _is_dataclass_def(node: ast.ClassDef) -> bool:
    for dec in node.decorator_list:
        name = dec.func if isinstance(dec, ast.Call) else dec
        if isinstance(name, ast.Attribute) and name.attr == "dataclass":
            return True
        if isinstance(name, ast.Name) and name.id == "dataclass":
            return True
    return False


def lint_budget_accounts(files: list[pathlib.Path]) -> list[str]:
    """Cross-file pass: collect every config dataclass field named
    `*_bytes` under horaedb_tpu/ and every ledger registration's
    account kind, then require each budget field to be mapped to a
    registered kind (or explicitly exempted).

    Budget fields and their registrations live in DIFFERENT files, so
    a subset invocation (`python tools/lint.py horaedb_tpu/storage/
    config.py`) must still see the whole package's registrations or
    every budget field in the subset false-positives — the scan set is
    the given files UNION the repo's horaedb_tpu/ tree."""
    budget_fields: list[tuple[str, int, str]] = []  # (file, line, field)
    registered_kinds: set[str] = set()
    scan = {p.resolve() for p in files if "horaedb_tpu" in str(p)}
    pkg = pathlib.Path(__file__).resolve().parent.parent / "horaedb_tpu"
    if pkg.is_dir():
        scan |= {p.resolve() for p in iter_files([str(pkg)])}
    for path in sorted(scan):
        try:
            tree = ast.parse(path.read_text(), filename=str(path))
        except SyntaxError:
            continue  # lint_file already reported it
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef) and _is_dataclass_def(node):
                for stmt in node.body:
                    if (isinstance(stmt, ast.AnnAssign)
                            and isinstance(stmt.target, ast.Name)
                            and stmt.target.id.endswith("_bytes")):
                        budget_fields.append(
                            (str(path), stmt.lineno, stmt.target.id))
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("register", "flow")
                    and any(n in ("memledger", "ledger", "_memledger")
                            for n in _receiver_chain(node.func))):
                kind = None
                for kw in node.keywords:
                    if (kw.arg == "kind"
                            and isinstance(kw.value, ast.Constant)):
                        kind = kw.value.value
                if (kind is None and node.args
                        and isinstance(node.args[0], ast.Constant)
                        and isinstance(node.args[0].value, str)):
                    kind = node.args[0].value.split(":", 1)[0]
                if kind:
                    registered_kinds.add(kind)
    problems = []
    for fname, lineno, field in budget_fields:
        if field in _BUDGET_FIELD_EXEMPT:
            continue
        kind = _BUDGET_FIELD_ACCOUNTS.get(field)
        if kind is None:
            problems.append(
                f"{fname}:{lineno}: byte-budget config field "
                f"{field!r} has no memory-ledger account mapping — add "
                "a ledger.register(...) at the owning component's open "
                "and map it in tools/lint.py _BUDGET_FIELD_ACCOUNTS "
                "(or exempt it with a reason)")
        elif kind not in registered_kinds:
            problems.append(
                f"{fname}:{lineno}: budget field {field!r} maps to "
                f"ledger account kind {kind!r} but no "
                f"ledger.register/flow call registers that kind under "
                "horaedb_tpu/")
    return problems


def main() -> int:
    paths = sys.argv[1:] or DEFAULT_PATHS
    all_problems: list[str] = []
    n = 0
    files = list(iter_files(paths))
    for f in files:
        n += 1
        all_problems.extend(lint_file(f))
    all_problems.extend(lint_budget_accounts(files))
    for p in all_problems:
        print(p)
    print(f"lint: {n} files, {len(all_problems)} problems",
          file=sys.stderr)
    return 1 if all_problems else 0


if __name__ == "__main__":
    sys.exit(main())
