#!/usr/bin/env python
"""Chunked-layout vs row-layout cold downsample at matched row counts.

VERDICT r4 item 8's yardstick: with the native batch chunk decoder, the
chunked cold path should land within 1.5x of the row-layout cold path.
Prints one JSON line with both cold p50s and the ratio.

Usage: python tools/chunked_vs_row.py [rows] (default 10M)
"""

import asyncio
import json
import sys
import time

from horaedb_tpu.utils.cpu_mesh import force_cpu_devices

force_cpu_devices(1)

import numpy as np  # noqa: E402
import pyarrow as pa  # noqa: E402

from horaedb_tpu.metric_engine import MetricEngine  # noqa: E402
from horaedb_tpu.objstore import MemoryObjectStore  # noqa: E402
from horaedb_tpu.storage.config import StorageConfig, from_dict  # noqa: E402
from horaedb_tpu.storage.types import TimeRange  # noqa: E402

HOUR = 3_600_000
SEGMENT_MS = 2 * HOUR


def log(msg):
    print(msg, file=sys.stderr, flush=True)


async def run_one(chunked: bool, rows: int) -> float:
    hosts = 100
    interval = 10_000
    per_host = rows // hosts
    span = per_host * interval
    T0 = (1_700_000_000_000 // SEGMENT_MS) * SEGMENT_MS
    rng = np.random.default_rng(0)
    n = per_host * hosts
    ts = T0 + np.repeat(np.arange(per_host, dtype=np.int64) * interval,
                        hosts)
    host_id = np.tile(np.arange(hosts, dtype=np.int32), per_host)
    # 1-decimal gauges: the chunk codec's scaled-int sweet spot
    vals = np.round(rng.random(n) * 100, 1)
    names = pa.array([f"host_{i:03d}" for i in range(hosts)])

    cfg = from_dict(StorageConfig, {
        "scheduler": {"schedule_interval": "1h"},
        "scan": {"cache_max_rows": rows * 4}})
    e = await MetricEngine.open("cvr", MemoryObjectStore(),
                                segment_ms=SEGMENT_MS, config=cfg,
                                chunked_data=chunked)
    try:
        t0 = time.perf_counter()
        chunk = max(1, 1_000_000 // hosts) * hosts
        for lo in range(0, n, chunk):
            hi = min(n, lo + chunk)
            await e.write_arrow("cpu", ["host"], pa.record_batch({
                "host": pa.DictionaryArray.from_arrays(
                    pa.array(host_id[lo:hi]), names),
                "timestamp": pa.array(ts[lo:hi], type=pa.int64()),
                "value": pa.array(vals[lo:hi], type=pa.float64()),
            }))
        log(f"{'chunked' if chunked else 'row'}: ingest {n:,} rows in "
            f"{time.perf_counter() - t0:.1f}s")

        async def q():
            return await e.query_downsample(
                "cpu", [], TimeRange.new(T0, T0 + span),
                bucket_ms=60_000, aggs=("avg",))

        out = await q()  # compile/warm
        assert len(out["tsids"]) == hosts
        times = []
        for _ in range(3):
            if chunked:
                if e._chunk_cache is not None:
                    e._chunk_cache.clear()
            else:
                e.tables["data"].reader.scan_cache.clear()
            t0 = time.perf_counter()
            out = await q()
            times.append(time.perf_counter() - t0)
        return float(np.percentile(times, 50))
    finally:
        await e.close()


def main() -> None:
    rows = int(sys.argv[1]) if len(sys.argv) > 1 else 10_000_000
    row_p50 = asyncio.run(run_one(False, rows))
    chunk_p50 = asyncio.run(run_one(True, rows))
    out = {
        "metric": f"chunked vs row cold downsample, {rows / 1e6:.0f}M rows",
        "row_cold_p50_ms": round(row_p50 * 1e3, 1),
        "chunked_cold_p50_ms": round(chunk_p50 * 1e3, 1),
        "chunked_vs_row": round(chunk_p50 / row_p50, 2),
    }
    log(f"row cold {row_p50 * 1e3:.0f} ms, chunked cold "
        f"{chunk_p50 * 1e3:.0f} ms -> {out['chunked_vs_row']}x")
    print(json.dumps(out))


if __name__ == "__main__":
    main()
