#!/usr/bin/env python
"""Scale ladder: the headline engine workload at 10/20/50/100/200M rows.

Each rung runs bench.py config 1 in a FRESH subprocess (isolated RSS
baseline, CPU backend pinned — the axon relay must never be probed from
a loop like this).  Rows scale by CARDINALITY past 20M (BENCH_HOSTS
grows at a fixed 200k-tick span) because a single query window is
bounded by int32 ms offsets — the TSBS-devops shape of "more rows" is
more hosts anyway.

Writes bench_results/scale_r6.md (curve + 1B projection) and
bench_results/scale_proven.json {max_rows_proven} which bench.py
surfaces in every driver payload.  Round 6 is the sparse-combine
re-measure: same rungs, same columns as r5, so the r5 observation
("cold p50 scales 4.39x linear from 10M to 200M, cause =
combine/finalize materializing the hosts x buckets output grid") is
directly comparable.

Usage: python tools/scale_run.py [--max-rows 200000000] [--iters 5]
"""

import argparse
import datetime
import json
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

LADDER = [10_000_000, 20_000_000, 50_000_000, 100_000_000, 200_000_000]
TICKS = 200_000  # span 2e9 ms < 2^31; hosts = rows / TICKS past 20M


def rung_env(rows: int) -> dict:
    env = dict(os.environ,
               _HORAEDB_BENCH_REEXEC="1",  # never probe the relay here
               PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu",
               BENCH_ROWS=str(rows),
               BENCH_ITERS=str(ARGS.iters))
    if rows > 20_000_000:
        env["BENCH_HOSTS"] = str(rows // TICKS)
    return env


def run_rung(rows: int) -> dict:
    print(f"=== {rows / 1e6:.0f}M rows ===", flush=True)
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "bench.py")],
        env=rung_env(rows), capture_output=True, text=True,
        timeout=ARGS.timeout)
    sys.stderr.write(proc.stderr[-2000:])
    if proc.returncode != 0:
        return {"rows": rows, "failed": True,
                "error": proc.stderr.strip().splitlines()[-1]
                if proc.stderr.strip() else f"exit {proc.returncode}"}
    line = proc.stdout.strip().splitlines()[-1]
    out = json.loads(line)
    out["hosts"] = int(rung_env(rows).get("BENCH_HOSTS", 100))
    return out


def fmt_row(r: dict) -> str:
    if r.get("failed"):
        return f"| {r['rows'] / 1e6:.0f}M | FAILED: {r['error']} |||||||"
    return ("| {rm:.0f}M | {hosts} | {cold:.0f} | {var} | {cach:.1f} | "
            "{rps:.1f}M | {rss:.1f} | {ing} |").format(
        rm=r["rows"] / 1e6, hosts=r["hosts"],
        cold=r["cold_p50_ms"],
        var=(f"{r['varied_p50_ms']:.1f}"
             if r.get("varied_p50_ms") is not None else "—"),
        cach=r["value"],
        rps=r["rows_per_s_cold"] / 1e6,
        rss=r.get("max_rss_mb", 0) / 1024,
        ing=r.get("ingest_s", "—"))


def main() -> None:
    results = []
    for rows in LADDER:
        if rows > ARGS.max_rows:
            break
        results.append(run_rung(rows))
        with open(os.path.join(ROOT, "bench_results",
                               "scale_ladder_raw.json"), "w") as f:
            json.dump(results, f, indent=1)
    ok = [r for r in results if not r.get("failed")]
    if not ok:
        sys.exit("every rung failed")
    proven = max(r["rows"] for r in ok)
    date = datetime.date.today().isoformat()
    with open(os.path.join(ROOT, "bench_results",
                           "scale_proven.json"), "w") as f:
        json.dump({"max_rows_proven": proven, "date": date,
                   "source": "bench_results/scale_r6.md",
                   "backend": ok[-1].get("backend", "cpu")}, f, indent=1)

    lines = [
        f"# Scale ladder, round 6 ({date})",
        "",
        "Headline workload (config 1: ingest -> cold/varied/cached "
        "downsample) at rising row counts.  Backend: "
        f"{ok[-1].get('backend')} (fallback={ok[-1].get('fallback')}).  "
        f"Rows scale by cardinality past 20M (hosts = rows / {TICKS:,}; "
        "a single query window is int32-ms bounded).",
        "",
        "| rows | hosts | cold p50 ms | varied p50 ms | cached p50 ms "
        "| cold Mrows/s | peak RSS GiB | ingest s |",
        "|---|---|---|---|---|---|---|---|",
    ]
    lines += [fmt_row(r) for r in results]
    lines += ["", "## Observations", ""]
    if len(ok) >= 2:
        a, b = ok[0], ok[-1]
        ratio = (b["cold_p50_ms"] / a["cold_p50_ms"]) / (
            b["rows"] / a["rows"])
        lines.append(
            f"- Cold p50 scales {ratio:.2f}x linear from "
            f"{a['rows'] / 1e6:.0f}M to {b['rows'] / 1e6:.0f}M "
            f"(cold throughput {a['rows_per_s_cold'] / 1e6:.1f} -> "
            f"{b['rows_per_s_cold'] / 1e6:.1f} Mrows/s), vs **4.39x "
            "beyond linear on r5**.  Round 6 is the same workload "
            "re-measured after the sparse combine "
            "(storage/combine.py): combine/finalize now pastes "
            "per-window partials straight into one requested-aggs "
            "output set (in-place column-slice runs) instead of "
            "fancy-indexed f64 accumulator grids for all six "
            "aggregates plus np.where output copies, so the "
            "output-grid term scales with touched cells rather than "
            "hosts x buckets x grids.  The varied leg grows "
            f"{ok[-1]['varied_p50_ms'] / ok[0]['varied_p50_ms']:.0f}x "
            f"across a {b['rows'] / a['rows']:.0f}x row range "
            "(dashboards bound the output grid; narrowed refinements "
            "additionally ride the delta-summation memo — bench "
            "config 14's refine leg).")
        rss_per_row = b.get("max_rss_mb", 0) * 1024 * 1024 / b["rows"]
        lines.append(
            f"- Peak RSS at {b['rows'] / 1e6:.0f}M: "
            f"{b.get('max_rss_mb', 0) / 1024:.1f} GiB "
            f"({rss_per_row:.0f} B/row, in-memory store holds parquet + "
            "sidecar + caches).")
        proj_cold = b["cold_p50_ms"] * (1e9 / b["rows"]) / 1e3
        proj_rss = rss_per_row * 1e9 / 2**30
        lines += [
            "",
            "## 1B projection",
            "",
            f"- Cold full-scan p50 at 1B at the 200M rung's throughput "
            f"({b['rows_per_s_cold'] / 1e6:.1f} Mrows/s): "
            f"~{proj_cold:.0f} s single-process.  The north-star 1B "
            "workload is a 64-SST merge-scan with a bounded output "
            "(top-k), which since ISSUE 9 is a real pushdown: "
            "combine_top_k materializes O(k x buckets) output cells "
            "regardless of host cardinality (bench config 14 asserts "
            "this against the scan_combine_materialized counter), so "
            "the per-row scan rate is the honest basis — "
            "~85-100 s/chip, to be divided across chips by the "
            "cluster tier's time-axis sharding.",
            f"- Projected peak RSS at 1B with the in-memory store: "
            f"~{proj_rss:.0f} GiB — past this box's 125 GiB, so 1B "
            "needs the S3/local store (parquet+sidecar on disk; the "
            "scan path streams windows and is not resident-bound) "
            "and/or the cluster tier's time-axis sharding.",
            "- What breaks first: (1) the in-memory object store's "
            "resident copy of parquet+sidecar bytes; (2) cached-mode "
            "HBM/RAM budget (scan.cache_max_rows) forces eviction — "
            "varied queries then pay cold per segment; (3) nothing in "
            "the manifest/compaction path: file counts stay in the "
            "hundreds.  The combine/finalize output grid — r5's item "
            "(3) — no longer leads: full-span output is one "
            "requested-aggs grid set and top-k/refine workloads bound "
            "or reuse it (config 14).",
        ]
    with open(os.path.join(ROOT, "bench_results", "scale_r6.md"),
              "w") as f:
        f.write("\n".join(lines) + "\n")
    print("\n".join(lines))


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--max-rows", type=int, default=200_000_000)
    p.add_argument("--iters", type=int, default=5)
    p.add_argument("--timeout", type=int, default=3600)
    ARGS = p.parse_args()
    main()
