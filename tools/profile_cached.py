"""Profile the CACHED query path (scan-cache hit, all stacks memoized).

Usage:  JAX_PLATFORMS=cpu python tools/profile_cached.py [rows]

Prints a cProfile of repeated cached query_downsample calls plus a
wall-clock breakdown, to attribute the residual per-query host time
(ROADMAP round-3 priority 1: trim per-query asyncio hops).
"""
import asyncio
import cProfile
import io
import os
import pstats
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if os.environ.get("JAX_PLATFORMS", "") == "cpu":
    # the axon sitecustomize hook forces jax_platforms="axon,cpu" and
    # dials the tunnel on backend init even when the env var says cpu;
    # the config override must happen before first backend use
    from horaedb_tpu.utils.cpu_mesh import force_cpu_devices
    force_cpu_devices(1)

import numpy as np
import pyarrow as pa


async def main(rows: int, iters: int) -> None:
    from horaedb_tpu.metric_engine import MetricEngine
    from horaedb_tpu.objstore import MemoryObjectStore
    from horaedb_tpu.storage.config import StorageConfig, from_dict
    from horaedb_tpu.storage.types import TimeRange

    hosts = 100
    interval = 10_000
    bucket_ms = 60_000
    per_host = max(1, rows // hosts)
    span = per_host * interval
    segment_ms = 2 * 3600 * 1000
    T0 = (1_700_000_000_000 // segment_ms) * segment_ms
    rng = np.random.default_rng(0)
    n = per_host * hosts
    ts = T0 + np.repeat(np.arange(per_host, dtype=np.int64) * interval, hosts)
    host_id = np.tile(np.arange(hosts, dtype=np.int32), per_host)
    vals = (rng.random(n) * 100).astype(np.float64)
    names = pa.array([f"host_{i:03d}" for i in range(hosts)])

    cfg = from_dict(StorageConfig, {
        "scheduler": {"schedule_interval": "1h"},
        "scan": {"cache_max_rows": rows * 4},
    })
    e = await MetricEngine.open("bench", MemoryObjectStore(),
                                segment_ms=segment_ms, config=cfg)
    chunk = max(1, 1_000_000 // hosts) * hosts
    for lo in range(0, n, chunk):
        hi = min(n, lo + chunk)
        batch = pa.record_batch({
            "host": pa.DictionaryArray.from_arrays(
                pa.array(host_id[lo:hi]), names),
            "timestamp": pa.array(ts[lo:hi], type=pa.int64()),
            "value": pa.array(vals[lo:hi], type=pa.float64()),
        })
        await e.write_arrow("cpu", ["host"], batch)

    async def query():
        return await e.query_downsample(
            "cpu", [], TimeRange.new(T0, T0 + span), bucket_ms=bucket_ms,
            aggs=("avg",))

    # warm: compile + populate caches
    await query()
    await query()

    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        await query()
        times.append(time.perf_counter() - t0)
    print(f"cached p50 {np.percentile(times, 50) * 1e3:.2f} ms  "
          f"min {min(times) * 1e3:.2f} ms  over {iters} iters")

    pr = cProfile.Profile()
    pr.enable()
    for _ in range(iters):
        await query()
    pr.disable()
    s = io.StringIO()
    ps = pstats.Stats(pr, stream=s).sort_stats("cumulative")
    ps.print_stats(45)
    print(s.getvalue())
    await e.close()


if __name__ == "__main__":
    rows = int(sys.argv[1]) if len(sys.argv) > 1 else 2_000_000
    iters = int(sys.argv[2]) if len(sys.argv) > 2 else 20
    asyncio.run(main(rows, iters))
